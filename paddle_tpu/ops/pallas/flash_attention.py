"""Flash attention Pallas TPU kernel.

TPU-native replacement for the reference's fused CUDA attention
(paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h — which
materializes the full O(s^2) score matrix). This kernel implements the
online-softmax streaming algorithm: scores never leave VMEM, HBM traffic is
O(s*d), and the MXU sees back-to-back (bq x d)@(d x bk) and (bq x bk)@(bk x d)
matmuls.

Design notes (measured on v5e at B=8, H=12, S=2048, D=128, bf16):
- K/V stay RESIDENT in VMEM for the whole kv walk (full-seq BlockSpec) and
  the walk is a fori_loop — measured faster (337ms train step) than
  streaming kv blocks through an innermost grid dimension with scratch
  accumulators (366ms): resident K/V costs zero DMA inside the loop. The
  resident footprint grows with S, and the chip showed the 512x512-block
  kernels overflow the 16M scoped-vmem budget at S=8192 (21M) — so
  `_resolve_blocks` runs a fit model that shrinks blocks as S grows and
  switches to the grid-streamed kernel variants (O(block) VMEM at any S)
  past the resident frontier. Multi-chip long context should still shard
  over the 'sep' mesh axis (ring attention); streaming is the single-chip
  escape hatch.
- Matmul operands stay in their storage dtype (bf16 runs the MXU at full
  rate; f32 at half), accumulating in f32 via preferred_element_type.
- Softmax runs in the exp2 domain with sm_scale*log2e folded into q (or k)
  once per kernel invocation; lse is stored in the natural-log domain.
- Masking every live block measured faster than lax.cond diagonal-only
  masking (cond defeats Mosaic's loop pipelining).

Layout: (batch, heads, seq, head_dim). Forward saves per-row logsumexp for
the backward pass; backward recomputes block scores (flash-style) to form
dQ/dK/dV without the s^2 buffer.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...jax_compat import tpu_compiler_params

# jax renamed TPUCompilerParams -> CompilerParams (version-bridged in
# one place, jax_compat)
_CompilerParams = tpu_compiler_params()


def _interpret() -> bool:
    # CPU backend (tests / sim meshes) runs kernels in interpreter mode
    import jax
    return jax.default_backend() == "cpu"

DEFAULT_BLOCK_Q = None  # auto: largest of 512/256/128 dividing the seq
DEFAULT_BLOCK_K = None
NEG_INF = -1e30
LOG2E = 1.4426950408889634
LN2 = 0.6931471805599453


def flash_eligible(seq_len: int, head_dim: int, dtype) -> bool:
    """The one shape/dtype gate for every flash-attention entry point
    (model layers, Ulysses, ring — they must never diverge): kernel
    supports 128-multiple sequences >= 256 and MXU-tiled head dims,
    under the FLAGS_use_flash_attention switch."""
    from ...core import flags as _flags
    return (bool(_flags.get_flag("use_flash_attention"))
            and seq_len >= 256 and seq_len % 128 == 0
            and head_dim in (64, 128, 256)
            and dtype in (jnp.float32, jnp.bfloat16))


def _pick_block(seq_len: int) -> int:
    # Measured on v5e at (B8,H12,S2048,D128) fwd+bwd: 512 blocks run 11.6ms
    # vs 18.4ms at the MXU-tile minimum of 128 — bigger blocks amortize the
    # grid/loop overhead and keep the MXU busy; 1024 is no faster and eats
    # VMEM headroom.
    for cand in (512, 256, 128):
        if seq_len % cand == 0:
            return cand
    # Correctness fallback for non-128-multiple sequences: the block MUST
    # divide seq_len (grid steps would otherwise skip output rows / kv
    # positions) and stay sublane-aligned for Mosaic (multiple of 8) —
    # including seq_len <= 128, where returning seq_len verbatim would hand
    # Mosaic an unaligned sublane count (e.g. S=100).
    for cand in range(min(128, seq_len), 7, -1):
        if seq_len % cand == 0 and cand % 8 == 0:
            return cand
    raise ValueError(
        f"flash_attention: no sublane-aligned block divides seq_len="
        f"{seq_len}; pad the sequence to a multiple of 128")


# Scoped-VMEM fit model, calibrated on chip (v5e, 16M scoped limit).
# Chip facts driving the coefficients (tools/long8k_vmem_repro.py,
# 2026-08-01 window, D=128 bf16):
#   fwd  resident 512x512 @ S=8192  COMPILES      -> fwd temps <= ~7M
#   f+b  resident 512x512 @ S=8192  FAILS @17.00M -> ~16M + ~1M
#   f+b  resident 256x256 @ S=8192  FAILS @16.50M -> ~16M + ~0.5M
#   fwd  resident 256     @ S=16384 FAILS @16.50M -> resident alone 16M
#   f+b  streamed 512x512 @ S=8192  COMPILES
# The backward failures sit at ~2x the bf16 resident bytes plus a small
# block term: the dk/dv kernel's full-length operands (Q, dO) are ALSO
# materialized as f32 compute copies (2 x Sres x D x 4B), which the
# round-3 model missed — its coef-13 block term was calibrated against
# what was actually this S-scaled backward failure. Forward temps are
# block-sized only (s/p/exp2/acc/iota ~ a few (bq,bk) f32 buffers).
_SCOPED_VMEM = 16 * 2**20
_TEMP_COEF = 6        # fwd/dq: (bq,bk) f32-buffer equivalents, safe side
_BWD_TEMP_COEF = 2    # dk/dv block temps (chip: ~1-2 buffer equivalents)
_FIT_MARGIN = 2**20


def _resident_fits(bq, bk, Sres, D, itemsize=2, bwd=False) -> bool:
    # Sres: the longest sequence any resident-mode kernel holds full-length
    # in VMEM — Sk for the forward/dq kernels (K+V resident), and
    # max(Sq, Sk) on the backward path (the dk/dv kernel keeps Q+dO
    # resident at Sq)
    resident = 2 * 2 * Sres * D * itemsize  # 2 tensors, double-buffered
    if bwd:
        # full-length f32 compute copies of the resident pair (chip-
        # calibrated: the 17.00M/16.50M failures above)
        resident += 2 * Sres * D * 4
        temps = _BWD_TEMP_COEF * bq * bk * 4
    else:
        temps = _TEMP_COEF * bq * bk * 4
    return resident + temps + _FIT_MARGIN <= _SCOPED_VMEM


def _stream_fits(bq, bk, D, itemsize=2) -> bool:
    # streamed path: no resident K/V; scratch acc/m/l + double-buffered
    # q/k/v/o block streams + the same f32 temporaries
    scratch = bq * D * 4 + 2 * bq * 4
    streams = 2 * 2 * (2 * bq + 2 * bk) * D * itemsize
    temps = _TEMP_COEF * bq * bk * 4
    return scratch + streams + temps + _FIT_MARGIN <= _SCOPED_VMEM


# Canonical block-pair preference, best-first from the v5e fwd+bwd
# measurements at S=2048/D=128 (512x512 = 11.6ms, 256x512 = 13.6ms,
# 256x256 = 15.1ms, 128x128 = 18.4ms). autotune._FA_BLOCKS derives from
# this list so the tuner and the resolver can never disagree.
MEASURED_BLOCK_ORDER = ((512, 512), (256, 512), (512, 256), (256, 256),
                        (128, 512), (512, 128), (128, 128))
_PAIR_ORDER = MEASURED_BLOCK_ORDER[:-1] + ((128, 256), (256, 128),
                                           (128, 128))
# Backward-kernel preference, from the on-chip 3x3 sweep at S=2048/D=128
# (tools/flash_bwd_sweep.py, 2026-08-01): 1024x512 measured fastest
# (13.51 ms/fwd+bwd vs 13.68 at 512x512); taller dq blocks amortize the
# full-length kv walk. Tried first when S divides; everything after
# falls back to the shared order.
_BWD_PAIR_ORDER = ((1024, 512),) + _PAIR_ORDER


def _resolve_blocks(Sq, Sk, block_q, block_k, D=128, itemsize=2,
                    stream=None, bwd=False):
    """Pick (block_q, block_k, streamed). Explicit blocks are honored
    verbatim (sweeps/experiments own the consequences); auto-pick walks
    the measured-fast pairs largest-first and returns the first that
    fits the scoped-VMEM model with K/V resident, else falls back to the
    grid-streamed kernels (unbounded S at O(block) VMEM). ``stream``
    True/False forces the mode; None decides from the fit model.
    ``bwd`` widens the resident term to max(Sq, Sk): the dk/dv kernel
    keeps Q+dO resident at Sq where the forward keeps K+V at Sk."""
    Sres = max(Sq, Sk) if bwd else Sk
    if block_q and block_k:
        if stream is None:
            stream = not _resident_fits(block_q, block_k, Sres, D,
                                        itemsize, bwd)
        return block_q, block_k, stream
    seen = set()
    cands = []
    for bq, bk in (_BWD_PAIR_ORDER if bwd else _PAIR_ORDER):
        cq, ck = block_q or bq, block_k or bk
        if (cq, ck) in seen or Sq % cq or Sk % ck:
            continue
        seen.add((cq, ck))
        cands.append((cq, ck))
    if stream:
        for cq, ck in cands:
            if _stream_fits(cq, ck, D, itemsize):
                return cq, ck, True
        # forced streaming with no fitting 128-multiple pair: divisor
        # blocks are <=128 and always stream-fit
        return (block_q or _pick_block(Sq), block_k or _pick_block(Sk),
                True)
    for cq, ck in cands:
        if _resident_fits(cq, ck, Sres, D, itemsize, bwd):
            return cq, ck, False
    if stream is None:
        for cq, ck in cands:
            if _stream_fits(cq, ck, D, itemsize):
                return cq, ck, True
    # no 128-multiple pair divides S: divisor-search blocks are <=128.
    # They may still not make RESIDENT K/V fit (odd does not imply
    # tiny) — honor the fit model and stream when it says no, unless
    # the caller forced resident and owns the compile outcome.
    cq = block_q or _pick_block(Sq)
    ck = block_k or _pick_block(Sk)
    if stream is False and cands:
        return cands[0][0], cands[0][1], False
    if stream is False:
        return cq, ck, False
    return cq, ck, not _resident_fits(cq, ck, Sres, D, itemsize, bwd)


def _mask_causal(s, qi, kj, block_q, block_k):
    """NEG_INF-mask score entries above the causal diagonal for the
    (qi, kj) block pair — shared by all six kernel variants."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal,
                block_q, block_k, kv_len):
    qi = pl.program_id(1)
    q = q_ref[0]  # (block_q, d)
    # fold sm_scale*log2e into q once: scores leave the MXU already in the
    # exp2 domain with no per-block rescale
    q2 = (q.astype(jnp.float32) * (sm_scale * LOG2E)).astype(q.dtype)

    m = jnp.full((block_q,), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)

    num_kv = kv_len // block_k
    if causal:
        # only blocks at or before the diagonal contribute
        num_live = jnp.minimum(((qi + 1) * block_q + block_k - 1) // block_k,
                               num_kv)
    else:
        num_live = num_kv

    def body(kj, carry):
        m, l, acc = carry
        k = k_ref[0, pl.dslice(kj * block_k, block_k)]
        v = v_ref[0, pl.dslice(kj * block_k, block_k)]
        s = jax.lax.dot_general(q2, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _mask_causal(s, qi, kj, block_q, block_k)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp2(s - m_new[:, None])
        alpha = jnp.exp2(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_live, body, (m, l, acc))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lse is saved in the natural-log domain (bwd converts back)
    lse_ref[0] = (LN2 * m + jnp.log(l_safe))[:, None].astype(jnp.float32)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, sm_scale, causal, block_q, block_k, kv_len):
    qi = pl.program_id(1)
    q = q_ref[0]
    q2 = (q.astype(jnp.float32) * (sm_scale * LOG2E)).astype(q.dtype)
    do = do_ref[0]
    lse2 = lse_ref[0, :, 0] * LOG2E  # exp2-domain logsumexp
    delta = delta_ref[0, :, 0]
    dq = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)
    num_kv = kv_len // block_k
    if causal:
        num_live = jnp.minimum(((qi + 1) * block_q + block_k - 1) // block_k,
                               num_kv)
    else:
        num_live = num_kv

    def body(kj, dq):
        k = k_ref[0, pl.dslice(kj * block_k, block_k)]
        v = v_ref[0, pl.dslice(kj * block_k, block_k)]
        s = jax.lax.dot_general(q2, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _mask_causal(s, qi, kj, block_q, block_k)
        p = jnp.exp2(s - lse2[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        return dq + jax.lax.dot_general(ds.astype(k.dtype), k,
                                        (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_live, body, dq)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, sm_scale, causal, block_q, block_k,
                    q_len):
    kj = pl.program_id(1)
    k = k_ref[0]  # (block_k, d)
    v = v_ref[0]
    # fold sm_scale*log2e into k once (dk accumulation uses unscaled q)
    k2 = (k.astype(jnp.float32) * (sm_scale * LOG2E)).astype(k.dtype)
    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)
    num_q = q_len // block_q
    if causal:
        first_live = (kj * block_k) // block_q
    else:
        first_live = 0

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.dslice(qi * block_q, block_q)]
        do = do_ref[0, pl.dslice(qi * block_q, block_q)]
        lse2 = lse_ref[0, pl.dslice(qi * block_q, block_q), 0] * LOG2E
        delta = delta_ref[0, pl.dslice(qi * block_q, block_q), 0]
        s = jax.lax.dot_general(q, k2, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _mask_causal(s, qi, kj, block_q, block_k)
        p = jnp.exp2(s - lse2[:, None])  # (bq, bk)
        dv_new = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dk_new = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk, dv = jax.lax.fori_loop(first_live, num_q, body, (dk, dv))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ---- grid-streamed variants (long sequences) ----
#
# Beyond the resident-KV frontier (~14k at D=128: double-buffered K+V
# alone approach the 16M scoped-vmem limit) K/V blocks stream through an
# innermost grid dimension and the online-softmax state (m, l, acc)
# lives in VMEM scratch across grid steps — O(block) VMEM at any S.
# Measured 8% slower than resident at S=2048 (PERF.md round-2
# ablations), so the resolver only picks streaming when resident can't
# compile. Same math as the resident kernels; dead causal blocks skip
# compute via pl.when (the DMA still runs — acceptable for a fallback
# whose alternative is failing to compile).


def _fwd_kernel_stream(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                       acc_scr, *, sm_scale, causal, block_q, block_k,
                       num_kv):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _compute():
        q = q_ref[0]
        q2 = (q.astype(jnp.float32) * (sm_scale * LOG2E)).astype(q.dtype)
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q2, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _mask_causal(s, qi, kj, block_q, block_k)
        m = m_scr[...][:, 0]
        l = l_scr[...][:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp2(s - m_new[:, None])
        alpha = jnp.exp2(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1)
        acc_scr[...] = acc_scr[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = m_new[:, None]
        l_scr[...] = l_new[:, None]

    if causal:
        pl.when((qi + 1) * block_q > kj * block_k)(_compute)
    else:
        _compute()

    @pl.when(kj == num_kv - 1)
    def _flush():
        l = l_scr[...][:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0] = (LN2 * m_scr[...][:, 0] + jnp.log(l_safe))[
            :, None].astype(jnp.float32)


def _bwd_dq_kernel_stream(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dq_ref, dq_scr, *, sm_scale, causal, block_q,
                          block_k, num_kv):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    def _compute():
        q = q_ref[0]
        q2 = (q.astype(jnp.float32) * (sm_scale * LOG2E)).astype(q.dtype)
        do = do_ref[0]
        lse2 = lse_ref[0, :, 0] * LOG2E
        delta = delta_ref[0, :, 0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q2, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _mask_causal(s, qi, kj, block_q, block_k)
        p = jnp.exp2(s - lse2[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dq_scr[...] = dq_scr[...] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when((qi + 1) * block_q > kj * block_k)(_compute)
    else:
        _compute()

    @pl.when(kj == num_kv - 1)
    def _flush():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel_stream(q_ref, k_ref, v_ref, do_ref, lse_ref,
                           delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                           sm_scale, causal, block_q, block_k, num_q):
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    def _compute():
        k = k_ref[0]
        v = v_ref[0]
        k2 = (k.astype(jnp.float32) * (sm_scale * LOG2E)).astype(k.dtype)
        q = q_ref[0]
        do = do_ref[0]
        lse2 = lse_ref[0, :, 0] * LOG2E
        delta = delta_ref[0, :, 0]
        s = jax.lax.dot_general(q, k2, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _mask_causal(s, qi, kj, block_q, block_k)
        p = jnp.exp2(s - lse2[:, None])
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when((qi + 1) * block_q > kj * block_k)(_compute)
    else:
        _compute()

    @pl.when(qi == num_q - 1)
    def _flush():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_fwd_stream(q, k, v, causal, sm_scale, block_q, block_k):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    if Sq % block_q or Sk % block_k:
        raise ValueError(
            f"flash_attention blocks ({block_q},{block_k}) must divide "
            f"seq lens ({Sq},{Sk}); pass block_q/block_k=None to auto-pick")
    bh = B * H
    qr = q.reshape(bh, Sq, D)
    kr = k.reshape(bh, Sk, D)
    vr = v.reshape(bh, Sk, D)
    num_kv = Sk // block_k
    out, lse = functools.partial(pl.pallas_call, interpret=_interpret())(
        functools.partial(_fwd_kernel_stream, sm_scale=sm_scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          num_kv=num_kv),
        grid=(bh, Sq // block_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, t: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, t: (b, t, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, t: (b, t, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, t: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, t: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((bh, Sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qr, kr, vr)
    return out.reshape(B, H, Sq, D), lse[..., 0].reshape(B, H, Sq)


def _flash_bwd_stream(q, k, v, out, lse, do, causal, sm_scale, block_q,
                      block_k):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    if Sq % block_q or Sk % block_k:
        raise ValueError(
            f"flash_attention backward blocks ({block_q},{block_k}) must "
            f"divide seq lens ({Sq},{Sk})")
    bh = B * H
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(bh, Sq, 1)
    qr = q.reshape(bh, Sq, D)
    kr = k.reshape(bh, Sk, D)
    vr = v.reshape(bh, Sk, D)
    dor = do.reshape(bh, Sq, D)
    lser = lse.reshape(bh, Sq, 1)
    num_kv = Sk // block_k
    num_q = Sq // block_q

    dq = functools.partial(pl.pallas_call, interpret=_interpret())(
        functools.partial(_bwd_dq_kernel_stream, sm_scale=sm_scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          num_kv=num_kv),
        grid=(bh, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, t: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, t: (b, t, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, t: (b, t, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i, t: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, t: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, t: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, t: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, Sq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qr, kr, vr, dor, lser, delta)

    dk, dv = functools.partial(pl.pallas_call, interpret=_interpret())(
        functools.partial(_bwd_dkv_kernel_stream, sm_scale=sm_scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          num_q=num_q),
        grid=(bh, num_kv, num_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((bh, Sk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qr, kr, vr, dor, lser, delta)

    return (dq.reshape(B, H, Sq, D), dk.reshape(B, H, Sk, D),
            dv.reshape(B, H, Sk, D))


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    if Sq % block_q or Sk % block_k:
        raise ValueError(
            f"flash_attention blocks ({block_q},{block_k}) must divide "
            f"seq lens ({Sq},{Sk}); pass block_q/block_k=None to auto-pick")
    bh = B * H
    qr = q.reshape(bh, Sq, D)
    kr = k.reshape(bh, Sk, D)
    vr = v.reshape(bh, Sk, D)
    grid = (bh, Sq // block_q)
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=block_q, block_k=block_k, kv_len=Sk)
    out, lse = functools.partial(pl.pallas_call, interpret=_interpret())(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((bh, Sq, 1), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(qr, kr, vr)
    return out.reshape(B, H, Sq, D), lse[..., 0].reshape(B, H, Sq)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_core(q, k, v, causal=False, sm_scale=None,
                block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                bwd_block_q=None, bwd_block_k=None, stream=None):
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    block_q, block_k, streamed = _resolve_blocks(
        q.shape[2], k.shape[2], block_q, block_k, q.shape[-1],
        q.dtype.itemsize, stream)
    fwd = _flash_fwd_stream if streamed else _flash_fwd
    out, _ = fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return out


# When AUTO resolution lands in streamed mode for a causal self-attention,
# optionally route through the splash kernels with a lower-triangular
# block mask instead of the hand-written streamed variants. The theory
# (splash's prefetched kv_idx tables elide dead-block DMA, ~2x saved in
# the fwd/dQ walks) LOST on chip: at S=16384 the plain streamed kernels
# measure 48.3 ms/fwd+bwd vs 97.4 ms through splash-tril
# (tools/seq_attn_bench.py, 2026-08-01) — splash's per-block overhead
# (128/256 tiles, table machinery) outweighs the halved DMA, so the
# route is OFF. Kept as a switch so future splash block-size tuning can
# re-measure against the same yardstick.
CAUSAL_STREAM_VIA_SPLASH = False


def flash_attention(q, k, v, causal=False, sm_scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    bwd_block_q=None, bwd_block_k=None, stream=None):
    """q/k/v: (batch, heads, seq, head_dim). Returns same shape as q.

    ``bwd_block_q``/``bwd_block_k`` tile the two backward kernels
    independently of the forward (None = same as forward). The backward
    walks the opposite operand full-length per block (dq walks K/V,
    dk/dv walks Q), so its VMEM/pipelining optimum need not match the
    forward's — tools/flash_bwd_sweep.py measures the grid on chip.

    ``stream`` selects the K/V-streaming kernels (None = automatic:
    resident K/V while the scoped-VMEM fit model allows it, streaming
    beyond — long sequences where double-buffered resident K/V would
    blow the 16M scoped-vmem limit that interpret-mode tests can't see).
    The forward and backward resolve independently: at S=8192 the
    forward stays resident while the backward streams. Auto-streamed
    causal self-attention can route through splash-tril via
    CAUSAL_STREAM_VIA_SPLASH, but that route measured 2x slower on chip
    and is off (see the toggle's comment).
    """
    auto = (block_q is None and block_k is None and bwd_block_q is None
            and bwd_block_k is None and stream is None)
    if auto and causal and CAUSAL_STREAM_VIA_SPLASH \
            and q.shape[2] == k.shape[2] and q.shape[2] % 256 == 0:
        _, _, streamed = _resolve_blocks(
            q.shape[2], k.shape[2], None, None, q.shape[-1],
            q.dtype.itemsize)
        if streamed:
            import numpy as _np

            from .splash_attention import splash_attention
            bq = bk = 256
            n = q.shape[2] // bq
            bm = _np.tril(_np.ones((n, n), bool))
            return splash_attention(q, k, v, bm, True, sm_scale, bq, bk)
    return _flash_core(q, k, v, causal, sm_scale, block_q, block_k,
                       bwd_block_q, bwd_block_k, stream)


def _fa_fwd(q, k, v, causal, sm_scale, block_q, block_k,
            bwd_block_q, bwd_block_k, stream=None):
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    block_q, block_k, streamed = _resolve_blocks(
        q.shape[2], k.shape[2], block_q, block_k, q.shape[-1],
        q.dtype.itemsize, stream)
    fwd = _flash_fwd_stream if streamed else _flash_fwd
    out, lse = fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, sm_scale, block_q, block_k, bwd_block_q, bwd_block_k,
            stream, res, do, *, delta=None):
    # delta: optional precomputed sum(dO*O, -1) as (B,H,Sq) f32 — ring
    # attention calls this once per ring step with the SAME (dO, O), so
    # it hoists the reduction out of its scan instead of recomputing it
    # n times (advisor round-4 finding)
    q, k, v, out, lse = res
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    block_q, block_k, streamed = _resolve_blocks(
        q.shape[2], k.shape[2],
        bwd_block_q or block_q, bwd_block_k or block_k, q.shape[-1],
        q.dtype.itemsize, stream, bwd=True)
    # explicit bwd blocks skip the fwd path's validation; a non-dividing
    # block would silently leave output rows unwritten (grid truncation)
    if q.shape[2] % block_q or k.shape[2] % block_k:
        raise ValueError(
            f"flash_attention backward blocks ({block_q}, {block_k}) must "
            f"divide seq lens ({q.shape[2]}, {k.shape[2]})")
    if streamed:
        return _flash_bwd_stream(q, k, v, out, lse, do, causal, sm_scale,
                                 block_q, block_k)
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bh = B * H
    if delta is None:
        delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1)
    delta = delta.reshape(bh, Sq, 1)
    qr = q.reshape(bh, Sq, D)
    kr = k.reshape(bh, Sk, D)
    vr = v.reshape(bh, Sk, D)
    dor = do.reshape(bh, Sq, D)
    lser = lse.reshape(bh, Sq, 1)

    dq = functools.partial(pl.pallas_call, interpret=_interpret())(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, kv_len=Sk),
        grid=(bh, Sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, Sq, D), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(qr, kr, vr, dor, lser, delta)

    dk, dv = functools.partial(pl.pallas_call, interpret=_interpret())(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, q_len=Sq),
        grid=(bh, Sk // block_k),
        in_specs=[
            pl.BlockSpec((1, Sq, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, Sq, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, Sq, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, Sq, 1), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((bh, Sk, D), v.dtype),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(qr, kr, vr, dor, lser, delta)

    return (dq.reshape(B, H, Sq, D), dk.reshape(B, H, Sk, D),
            dv.reshape(B, H, Sk, D))


_flash_core.defvjp(_fa_fwd, _fa_bwd)
