"""Grouped-query (GQA/MQA) flash attention Pallas TPU kernel.

The reference has no GQA-aware fused attention (fused_attention_op.cu
predates GQA); the portable fallback repeats K/V across query groups
(jnp.repeat) which multiplies K/V HBM traffic and VMEM residency by
n_groups. This kernel keeps K/V at their true head count: each grid
program processes ALL G query heads that share one kv head, flattening
the group into the matmul M dimension — the MXU sees a (G*bq, d)@(d, bk)
score matmul (bigger, not more, calls) and K/V are fetched once per kv
head instead of once per query head.

Layouts: q (B, G*Hkv, S, D) with head order grouped by kv head
(h = kv_head * G + g — jnp.repeat convention); k/v (B, Hkv, S, D).
Same resident-KV fori-walk + exp2-domain design as flash_attention.py.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...jax_compat import tpu_compiler_params

# jax renamed TPUCompilerParams -> CompilerParams (version-bridged in
# one place, jax_compat)
_CompilerParams = tpu_compiler_params()

from .flash_attention import LN2, LOG2E, NEG_INF, _interpret, _pick_block


# f32-element budget for ONE (G*block_q, block_k) score/probability buffer
# (2 MB each; the kernel holds score + p + acc + resident K/V in VMEM).
_SCORE_ELEMS = 512 * 1024
# Row cap for the G*block_q dimension: q/q2/acc/out buffers are rows-tall
# regardless of block_k, so the score budget alone can't bound them.
# Measured on v5e: rows=4096 (MQA G=32, bq=128, bk=128) exceeds the 16M
# scoped-vmem limit by 912K even with the score budget satisfied.
_MAX_ROWS = 2048
# Resident K/V grows with Sk (the long8k chip failure mode of the MHA
# kernels); the GQA temp coefficient is bounded by the round-3 chip
# evidence — rows=1024 x bk=512 at S=2048 COMPILED (2M resident +
# C*rows*bk*4 <= 16M gives C <= 6.8). 6 is the provisional value;
# tools/long8k_vmem_repro.py's GQA section re-measures the frontier.
_GQA_TEMP_COEF = 6
_GQA_VMEM = 16 * 2**20 - 2**20  # scoped limit less margin


def _gqa_fits(rows, bk, Sk, D, itemsize):
    resident = 2 * 2 * Sk * D * itemsize  # K+V per kv head, double-buffered
    return resident + _GQA_TEMP_COEF * rows * bk * 4 <= _GQA_VMEM


class ResidentOverflowError(ValueError):
    """No reachable block pair fits resident K/V in scoped VMEM —
    grouped_flash_attention auto-delegates to coarse-tile splash
    streaming on this, other ValueErrors (bad shapes etc.) propagate."""


def _gqa_resolve_blocks(Sq, Sk, G, block_q, block_k, D=128, itemsize=2):
    """Group-aware block pick: score/probability buffers are (G*block_q,
    block_k) f32, so the JOINT product G*block_q*block_k is bounded — a
    per-axis cap alone lets rows grow unboundedly with G (MQA G=32 at the
    512 default block_k would put ~16 MB of f32 score buffers in VMEM and
    fail Mosaic compilation). Auto-picked blocks shrink (block_k first,
    then block_q down to the 8-sublane floor) until the product fits;
    user-pinned blocks are honored as given."""
    user_q, user_k = block_q is not None, block_k is not None
    if block_q is None:
        cap = max(128, 1024 // G)
        for cand in (512, 256, 128):
            if cand <= cap and Sq % cand == 0:
                block_q = cand
                break
        else:
            block_q = min(_pick_block(Sq), cap)
    # plain per-axis pick only: the group-aware caps below own the VMEM
    # bound for these kernels (the MHA resolver's resident-fit model is
    # calibrated for the non-grouped kernels and a hardcoded D/itemsize)
    bq = block_q or _pick_block(Sq)
    bk = block_k or _pick_block(Sk)
    # halving preserves divisibility (bk | Sk implies bk/2 | Sk)
    while G * bq > _MAX_ROWS and not user_q and bq > 8 \
            and (bq // 2) % 8 == 0:
        bq //= 2
    while G * bq * bk > _SCORE_ELEMS and not user_k and bk > 128:
        bk //= 2
    while G * bq * bk > _SCORE_ELEMS and not user_q and bq > 8 \
            and (bq // 2) % 8 == 0:
        bq //= 2
    # long-Sk resident term (auto blocks only): shrink until the resident
    # K/V plus temp buffers fit scoped VMEM
    while not _gqa_fits(G * bq, bk, Sk, D, itemsize) and not user_k \
            and bk > 128:
        bk //= 2
    while not _gqa_fits(G * bq, bk, Sk, D, itemsize) and not user_q \
            and bq > 8 and (bq // 2) % 8 == 0:
        bq //= 2
    if not (user_q or user_k) and not _gqa_fits(G * bq, bk, Sk, D,
                                                itemsize):
        # either resident K/V alone exceeds scoped VMEM (no block choice
        # can compile) or the shrink loops stalled on divisibility /
        # sublane alignment short of a fitting pair — both end in an
        # opaque Mosaic compile failure, so raise the typed error here.
        # grouped_flash_attention's public entry catches it and
        # delegates to the coarse-tile K/V-streaming splash kernels;
        # direct core callers see the message below.
        raise ResidentOverflowError(
            f"grouped_flash_attention: resident K/V at Sk={Sk} "
            f"(D={D}, {itemsize}B) cannot fit the 16M scoped-VMEM "
            f"budget at any block size; shard the sequence (ring "
            f"attention / 'sep' axis) or use splash/flash streaming "
            f"for single-chip sequences this long")
    return bq, bk


def _pos_grids(rows, block_k, qi, kj, block_q):
    """(q_pos, k_pos) grids for a (G*bq, bk) score block: row r belongs to
    query position qi*bq + (r % bq) — the group index g = r // bq shares
    positions across the G heads."""
    r = jax.lax.broadcasted_iota(jnp.int32, (rows, block_k), 0)
    q_pos = qi * block_q + jax.lax.rem(r, block_q)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (rows, block_k), 1)
    return q_pos, k_pos


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal,
                block_q, block_k, kv_len, groups):
    qi = pl.program_id(1)
    G = groups
    D = q_ref.shape[-1]
    rows = G * block_q
    q = q_ref[0].reshape(rows, D)  # (G, bq, D) -> (G*bq, D)
    q2 = (q.astype(jnp.float32) * (sm_scale * LOG2E)).astype(q.dtype)

    m = jnp.full((rows,), NEG_INF, jnp.float32)
    l = jnp.zeros((rows,), jnp.float32)
    acc = jnp.zeros((rows, D), jnp.float32)

    num_kv = kv_len // block_k
    if causal:
        num_live = jnp.minimum(((qi + 1) * block_q + block_k - 1) // block_k,
                               num_kv)
    else:
        num_live = num_kv

    def body(kj, carry):
        m, l, acc = carry
        k = k_ref[0, pl.dslice(kj * block_k, block_k)]
        v = v_ref[0, pl.dslice(kj * block_k, block_k)]
        s = jax.lax.dot_general(q2, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos, k_pos = _pos_grids(rows, block_k, qi, kj, block_q)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp2(s - m_new[:, None])
        alpha = jnp.exp2(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_live, body, (m, l, acc))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).reshape(G, block_q, D).astype(
        o_ref.dtype)
    lse_ref[0] = (LN2 * m + jnp.log(l_safe)).reshape(G, block_q, 1).astype(
        jnp.float32)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, sm_scale, causal, block_q, block_k, kv_len, groups):
    qi = pl.program_id(1)
    G = groups
    D = q_ref.shape[-1]
    rows = G * block_q
    q = q_ref[0].reshape(rows, D)
    q2 = (q.astype(jnp.float32) * (sm_scale * LOG2E)).astype(q.dtype)
    do = do_ref[0].reshape(rows, D)
    lse2 = lse_ref[0].reshape(rows) * LOG2E
    delta = delta_ref[0].reshape(rows)
    dq = jnp.zeros((rows, D), jnp.float32)
    num_kv = kv_len // block_k
    if causal:
        num_live = jnp.minimum(((qi + 1) * block_q + block_k - 1) // block_k,
                               num_kv)
    else:
        num_live = num_kv

    def body(kj, dq):
        k = k_ref[0, pl.dslice(kj * block_k, block_k)]
        v = v_ref[0, pl.dslice(kj * block_k, block_k)]
        s = jax.lax.dot_general(q2, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos, k_pos = _pos_grids(rows, block_k, qi, kj, block_q)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp2(s - lse2[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        return dq + jax.lax.dot_general(ds.astype(k.dtype), k,
                                        (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_live, body, dq)
    dq_ref[0] = dq.reshape(G, block_q, D).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale, causal,
                    block_q, block_k, num_q, groups):
    """Unlike the MHA kernel (full q/do resident in VMEM — fine at
    rows=block_q), the grouped q/do blocks are G-times taller, so the q
    walk streams through the innermost GRID dimension with dk/dv in VMEM
    scratch; Mosaic double-buffers the next q/do block DMA."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    G = groups
    D = q_ref.shape[-1]
    rows = G * block_q

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    live = (qi * block_q + block_q - 1 >= kj * block_k) if causal else True

    @pl.when(live)
    def _compute():
        k = k_ref[0]  # (block_k, D)
        v = v_ref[0]
        k2 = (k.astype(jnp.float32) * (sm_scale * LOG2E)).astype(k.dtype)
        q = q_ref[0].reshape(rows, D)
        do = do_ref[0].reshape(rows, D)
        lse2 = lse_ref[0].reshape(rows) * LOG2E
        delta = delta_ref[0].reshape(rows)
        s = jax.lax.dot_general(q, k2, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos, k_pos = _pos_grids(rows, block_k, qi, kj, block_q)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp2(s - lse2[:, None])  # (G*bq, bk)
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q - 1)
    def _flush():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _shapes(q, k):
    B, Hq, Sq, D = q.shape
    Hkv = k.shape[1]
    if Hq % Hkv:
        raise ValueError(f"query heads {Hq} not a multiple of kv heads {Hkv}")
    return B, Hq, Hkv, Hq // Hkv, Sq, D


def _gqa_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k):
    B, Hq, Hkv, G, Sq, D = _shapes(q, k)
    Sk = k.shape[2]
    if Sq % block_q or Sk % block_k:
        raise ValueError(
            f"grouped_flash_attention blocks ({block_q},{block_k}) must "
            f"divide seq lens ({Sq},{Sk})")
    bh = B * Hkv
    # head order: h = kv*G + g (jnp.repeat convention)
    qr = q.reshape(bh, G, Sq, D)
    kr = k.reshape(bh, Sk, D)
    vr = v.reshape(bh, Sk, D)
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=block_q, block_k=block_k, kv_len=Sk,
                               groups=G)
    out, lse = functools.partial(pl.pallas_call, interpret=_interpret())(
        kernel,
        grid=(bh, Sq // block_q),
        in_specs=[
            pl.BlockSpec((1, G, block_q, D), lambda b, i: (b, 0, i, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, G, block_q, D), lambda b, i: (b, 0, i, 0)),
            pl.BlockSpec((1, G, block_q, 1), lambda b, i: (b, 0, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, G, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((bh, G, Sq, 1), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(qr, kr, vr)
    return (out.reshape(B, Hq, Sq, D),
            lse.reshape(B, Hq, Sq))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _grouped_flash_core(q, k, v, causal=False, sm_scale=None,
                        block_q=None, block_k=None):
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    G = q.shape[1] // max(1, k.shape[1])
    block_q, block_k = _gqa_resolve_blocks(q.shape[2], k.shape[2], G,
                                           block_q, block_k,
                                           q.shape[-1], q.dtype.itemsize)
    out, _ = _gqa_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k)
    return out


def grouped_flash_attention(q, k, v, causal=False, sm_scale=None,
                            block_q=None, block_k=None):
    """q: (B, Hq, S, D); k/v: (B, Hkv, S, D) with Hq = G*Hkv. Equivalent to
    flash_attention over jnp.repeat(k/v, G, axis=1) without the repeat.

    Past the resident-K/V VMEM frontier (auto blocks only) the call
    delegates to the K/V-STREAMING splash kernels at the true kv-head
    count with coarse (pick_splash_blocks) tiles — so GQA long-context
    works on one chip instead of failing to compile. Block size decides
    this race: at the round-3 128-tiles splash lost to repeat+flash
    (46.2 vs 34.0 ms at S=16384/G=4), at 512-tiles it wins while moving
    G x less K/V (28.2 vs 34.0 ms; 18.6 vs 20.0 at S=8192 —
    tools/gqa_xlong_bench.py, 2026-08-01)."""
    G = q.shape[1] // max(1, k.shape[1])
    if block_q is None and block_k is None:
        try:
            bq, bk = _gqa_resolve_blocks(q.shape[2], k.shape[2], G, None,
                                         None, q.shape[-1],
                                         q.dtype.itemsize)
            # pass the resolved blocks through — the core (and its vjp)
            # would otherwise re-run the identical resolution
            return _grouped_flash_core(q, k, v, causal, sm_scale, bq, bk)
        except ResidentOverflowError:
            import numpy as _np

            from .splash_attention import (pick_splash_blocks,
                                           splash_attention)
            bq, bk = pick_splash_blocks(q.shape[2], k.shape[2], G)
            nq, nk = q.shape[2] // bq, k.shape[2] // bk
            # full causal = lower-triangular block mask (the token-exact
            # triangle applies in-kernel); non-causal or mismatched
            # tilings use the dense mask — still streamed, just no
            # block skipping
            if causal and nq == nk:
                bm = _np.tril(_np.ones((nq, nk), bool))
            else:
                bm = _np.ones((nq, nk), bool)
            return splash_attention(q, k, v, bm, causal, sm_scale, bq, bk)
    return _grouped_flash_core(q, k, v, causal, sm_scale, block_q,
                               block_k)


def _fa_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    G = q.shape[1] // max(1, k.shape[1])
    block_q, block_k = _gqa_resolve_blocks(q.shape[2], k.shape[2], G,
                                           block_q, block_k,
                                           q.shape[-1], q.dtype.itemsize)
    out, lse = _gqa_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, sm_scale, block_q, block_k, res, do):
    q, k, v, out, lse = res
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    G0 = q.shape[1] // max(1, k.shape[1])
    block_q, block_k = _gqa_resolve_blocks(q.shape[2], k.shape[2], G0,
                                           block_q, block_k,
                                           q.shape[-1], q.dtype.itemsize)
    B, Hq, Hkv, G, Sq, D = _shapes(q, k)
    Sk = k.shape[2]
    bh = B * Hkv
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(bh, G, Sq, 1)
    qr = q.reshape(bh, G, Sq, D)
    kr = k.reshape(bh, Sk, D)
    vr = v.reshape(bh, Sk, D)
    dor = do.reshape(bh, G, Sq, D)
    lser = lse.reshape(bh, G, Sq, 1)

    dq = functools.partial(pl.pallas_call, interpret=_interpret())(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, kv_len=Sk,
                          groups=G),
        grid=(bh, Sq // block_q),
        in_specs=[
            pl.BlockSpec((1, G, block_q, D), lambda b, i: (b, 0, i, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, G, block_q, D), lambda b, i: (b, 0, i, 0)),
            pl.BlockSpec((1, G, block_q, 1), lambda b, i: (b, 0, i, 0)),
            pl.BlockSpec((1, G, block_q, 1), lambda b, i: (b, 0, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, G, block_q, D), lambda b, i: (b, 0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, G, Sq, D), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(qr, kr, vr, dor, lser, delta)

    num_q = Sq // block_q
    dk, dv = functools.partial(pl.pallas_call, interpret=_interpret())(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_q=num_q,
                          groups=G),
        grid=(bh, Sk // block_k, num_q),
        in_specs=[
            pl.BlockSpec((1, G, block_q, D), lambda b, j, i: (b, 0, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, G, block_q, D), lambda b, j, i: (b, 0, i, 0)),
            pl.BlockSpec((1, G, block_q, 1), lambda b, j, i: (b, 0, i, 0)),
            pl.BlockSpec((1, G, block_q, 1), lambda b, j, i: (b, 0, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((bh, Sk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qr, kr, vr, dor, lser, delta)

    return (dq.reshape(B, Hq, Sq, D), dk.reshape(B, Hkv, Sk, D),
            dv.reshape(B, Hkv, Sk, D))


_grouped_flash_core.defvjp(_fa_fwd, _fa_bwd)
