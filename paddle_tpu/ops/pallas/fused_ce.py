"""Fused softmax-crossentropy Pallas kernel.

Replaces the reference's fused softmax+CE CUDA path
(paddle/fluid/operators/collective/c_softmax_with_cross_entropy_op.cu and
phi softmax_with_cross_entropy kernels): one VMEM pass computes the row
max, log-sum-exp and the label logit without materializing the (N, V)
softmax in HBM — on a 32k vocab that intermediate is the single largest
HBM write of the training loss. Backward is the closed form
softmax(x) - onehot(label), likewise tiled.

All rank-1 per-row operands (labels, loss, lse, grad) are carried as
(N, 1) so every block is rank-2: Mosaic requires rank-1 blocks to be
lane-aligned (multiples of 128), while an (R, 1) block only needs the
sublane rule (R % 8 == 0), which BLOCK_ROWS=16 satisfies.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


BLOCK_ROWS = 16
LANES = 128


def _ce_fwd_kernel(logits_ref, labels_ref, loss_ref, lse_ref):
    x = logits_ref[...].astype(jnp.float32)          # (R, V)
    lbl = labels_ref[...][:, 0]                      # (R, 1) -> (R,)
    m = jnp.max(x, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(x - m[:, None]), axis=-1))
    R, V = x.shape
    onehot = jax.lax.broadcasted_iota(jnp.int32, (R, V), 1) == lbl[:, None]
    label_logit = jnp.sum(jnp.where(onehot, x, 0.0), axis=-1)
    loss_ref[...] = (lse - label_logit)[:, None]
    lse_ref[...] = lse[:, None]


def _ce_bwd_kernel(logits_ref, labels_ref, lse_ref, g_ref, dx_ref):
    x = logits_ref[...].astype(jnp.float32)
    lbl = labels_ref[...][:, 0]
    lse = lse_ref[...][:, 0]
    g = g_ref[...][:, 0]
    p = jnp.exp(x - lse[:, None])
    R, V = x.shape
    onehot = (jax.lax.broadcasted_iota(jnp.int32, (R, V), 1)
              == lbl[:, None]).astype(jnp.float32)
    dx_ref[...] = ((p - onehot) * g[:, None]).astype(dx_ref.dtype)


def _rows_block(n):
    return min(BLOCK_ROWS, n)


def _fusable(n_rows: int, vocab: int) -> bool:
    """The TPU lowering needs lane-aligned V and whole row blocks; the CPU
    interpreter accepts anything."""
    if n_rows % _rows_block(n_rows):
        return False
    if _interpret():
        return True
    return vocab % LANES == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def softmax_cross_entropy(logits, labels):
    """Per-token CE loss. logits (N, V), labels (N,) int32 -> (N,) f32."""
    loss, _ = _ce_fwd(logits, labels)
    return loss


def _ce_fwd(logits, labels):
    N, V = logits.shape
    R = _rows_block(N)
    assert N % R == 0, (N, R)
    loss, lse = pl.pallas_call(
        _ce_fwd_kernel,
        grid=(N // R,),
        in_specs=[pl.BlockSpec((R, V), lambda i: (i, 0)),
                  pl.BlockSpec((R, 1), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((R, 1), lambda i: (i, 0)),
                   pl.BlockSpec((R, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((N, 1), jnp.float32),
                   jax.ShapeDtypeStruct((N, 1), jnp.float32)],
        interpret=_interpret(),
    )(logits, labels.astype(jnp.int32)[:, None])
    return loss[:, 0], lse[:, 0]


def _fwd(logits, labels):
    loss, lse = _ce_fwd(logits, labels)
    return loss, (logits, labels, lse)


def _bwd(res, g):
    logits, labels, lse = res
    N, V = logits.shape
    R = _rows_block(N)
    dx = pl.pallas_call(
        _ce_bwd_kernel,
        grid=(N // R,),
        in_specs=[pl.BlockSpec((R, V), lambda i: (i, 0)),
                  pl.BlockSpec((R, 1), lambda i: (i, 0)),
                  pl.BlockSpec((R, 1), lambda i: (i, 0)),
                  pl.BlockSpec((R, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((R, V), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, V), logits.dtype),
        interpret=_interpret(),
    )(logits, labels.astype(jnp.int32)[:, None], lse[:, None],
      g.astype(jnp.float32)[:, None])
    return dx, None


softmax_cross_entropy.defvjp(_fwd, _bwd)


def causal_lm_loss(logits, labels):
    """Mean CE over (B, S, V) logits vs (B, S) labels using the fused
    kernel when shapes allow; dense log_softmax fallback otherwise."""
    B, S, V = logits.shape
    flat = logits.reshape(B * S, V)
    lbl = labels.reshape(B * S)
    if _fusable(B * S, V):
        return jnp.mean(softmax_cross_entropy(flat, lbl))
    logp = jax.nn.log_softmax(flat.astype(jnp.float32), -1)
    return jnp.mean(-jnp.take_along_axis(logp, lbl[:, None], -1)[:, 0])
