"""Fused softmax-crossentropy Pallas kernel.

Replaces the reference's fused softmax+CE CUDA path
(paddle/fluid/operators/collective/c_softmax_with_cross_entropy_op.cu and
phi softmax_with_cross_entropy kernels): one VMEM pass computes the row
max, log-sum-exp and the label logit without materializing the (N, V)
softmax in HBM — on a 32k vocab that intermediate is the single largest
HBM write of the training loss. Backward is the closed form
softmax(x) - onehot(label), likewise tiled.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


BLOCK_ROWS = 16


def _ce_fwd_kernel(logits_ref, labels_ref, loss_ref, lse_ref):
    x = logits_ref[...].astype(jnp.float32)          # (R, V)
    lbl = labels_ref[...]                            # (R,)
    m = jnp.max(x, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(x - m[:, None]), axis=-1))
    R, V = x.shape
    onehot = jax.lax.broadcasted_iota(jnp.int32, (R, V), 1) == lbl[:, None]
    label_logit = jnp.sum(jnp.where(onehot, x, 0.0), axis=-1)
    loss_ref[...] = lse - label_logit
    lse_ref[...] = lse


def _ce_bwd_kernel(logits_ref, labels_ref, lse_ref, g_ref, dx_ref):
    x = logits_ref[...].astype(jnp.float32)
    lbl = labels_ref[...]
    lse = lse_ref[...]
    g = g_ref[...]
    p = jnp.exp(x - lse[:, None])
    R, V = x.shape
    onehot = (jax.lax.broadcasted_iota(jnp.int32, (R, V), 1)
              == lbl[:, None]).astype(jnp.float32)
    dx_ref[...] = ((p - onehot) * g[:, None]).astype(dx_ref.dtype)


def _rows_block(n):
    return min(BLOCK_ROWS, n)


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def softmax_cross_entropy(logits, labels):
    """Per-token CE loss. logits (N, V), labels (N,) int32 -> (N,) f32."""
    loss, _ = _ce_fwd(logits, labels)
    return loss


def _ce_fwd(logits, labels):
    N, V = logits.shape
    R = _rows_block(N)
    assert N % R == 0, (N, R)
    loss, lse = pl.pallas_call(
        _ce_fwd_kernel,
        grid=(N // R,),
        in_specs=[pl.BlockSpec((R, V), lambda i: (i, 0)),
                  pl.BlockSpec((R,), lambda i: (i,))],
        out_specs=[pl.BlockSpec((R,), lambda i: (i,)),
                   pl.BlockSpec((R,), lambda i: (i,))],
        out_shape=[jax.ShapeDtypeStruct((N,), jnp.float32),
                   jax.ShapeDtypeStruct((N,), jnp.float32)],
        interpret=_interpret(),
    )(logits, labels.astype(jnp.int32))
    return loss, lse


def _fwd(logits, labels):
    loss, lse = _ce_fwd(logits, labels)
    return loss, (logits, labels, lse)


def _bwd(res, g):
    logits, labels, lse = res
    N, V = logits.shape
    R = _rows_block(N)
    dx = pl.pallas_call(
        _ce_bwd_kernel,
        grid=(N // R,),
        in_specs=[pl.BlockSpec((R, V), lambda i: (i, 0)),
                  pl.BlockSpec((R,), lambda i: (i,)),
                  pl.BlockSpec((R,), lambda i: (i,)),
                  pl.BlockSpec((R,), lambda i: (i,))],
        out_specs=pl.BlockSpec((R, V), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, V), logits.dtype),
        interpret=_interpret(),
    )(logits, labels.astype(jnp.int32), lse, g.astype(jnp.float32))
    return dx, None


softmax_cross_entropy.defvjp(_fwd, _bwd)


def causal_lm_loss(logits, labels):
    """Mean CE over (B, S, V) logits vs (B, S) labels using the fused
    kernel when shapes allow; dense log_softmax fallback otherwise."""
    B, S, V = logits.shape
    flat = logits.reshape(B * S, V)
    lbl = labels.reshape(B * S)
    if (B * S) % _rows_block(B * S) == 0:
        return jnp.mean(softmax_cross_entropy(flat, lbl))
    logp = jax.nn.log_softmax(flat.astype(jnp.float32), -1)
    return jnp.mean(-jnp.take_along_axis(logp, lbl[:, None], -1)[:, 0])
