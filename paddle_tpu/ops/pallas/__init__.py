"""Pallas TPU kernels — the hand-kernel slots of the reference
(operators/fused/*.cu) rebuilt for the MXU/VMEM model."""
from .flash_attention import flash_attention  # noqa: F401
from .layer_norm import fused_layer_norm, fused_rms_norm  # noqa: F401
from .paged_attention import (PagedKVCache, paged_attention,  # noqa: F401
                              paged_prefill_attention)
