"""Fused dropout + residual-add + layernorm Pallas kernel.

~ the reference's fused_bias_dropout_residual_layer_norm family
(paddle/fluid/operators/fused/fused_attention_op.cu,
fused_dropout_helper.h): the transformer residual path
``ln(residual + dropout(x))`` done in one VMEM pass — the three
intermediates never round-trip HBM. Dropout randomness comes in as a
uint32 bits tensor generated with the framework Generator outside the
kernel (seed+offset reproducibility, phi/core/generator.h:23 semantics)
so the kernel itself stays deterministic.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


BLOCK_ROWS = 128


def _kernel(x_ref, res_ref, bits_ref, w_ref, b_ref, o_ref, *, p, eps,
            training):
    x = x_ref[...].astype(jnp.float32)
    if training and p > 0.0:
        # keep when uniform(bits) >= p; inverted scaling keeps E[out]=x
        u = bits_ref[...].astype(jnp.float32) / 4294967296.0
        keep = (u >= p).astype(jnp.float32)
        x = x * keep / (1.0 - p)
    h = x + res_ref[...].astype(jnp.float32)
    mu = jnp.mean(h, axis=-1, keepdims=True)
    hc = h - mu
    var = jnp.mean(hc * hc, axis=-1, keepdims=True)
    y = hc * jax.lax.rsqrt(var + eps)
    y = y * w_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


def fused_dropout_add_layer_norm(x, residual, weight, bias, p=0.1,
                                 eps=1e-5, training=True, bits=None):
    """x, residual: (..., H); weight/bias: (H,). Returns ln(res+drop(x)).

    bits: optional uint32 tensor shaped like x (dropout randomness); when
    None and training, drawn from the framework Generator.
    """
    orig_shape = x.shape
    H = orig_shape[-1]
    x2 = x.reshape(-1, H)
    r2 = residual.reshape(-1, H)
    N = x2.shape[0]
    R = min(BLOCK_ROWS, N)
    if N % R != 0:  # ragged: dense fallback keeps semantics
        xf = x2.astype(jnp.float32)
        if training and p > 0.0:
            if bits is None:
                from ...core.generator import default_generator
                bits = jax.random.bits(default_generator().next_key(),
                                       (N, H), jnp.uint32)
            u = bits.reshape(N, H).astype(jnp.float32) / 4294967296.0
            xf = xf * (u >= p).astype(jnp.float32) / (1.0 - p)
        h = xf + r2.astype(jnp.float32)
        mu = h.mean(-1, keepdims=True)
        var = ((h - mu) ** 2).mean(-1, keepdims=True)
        y = (h - mu) * jax.lax.rsqrt(var + eps) * weight + bias
        return y.astype(x.dtype).reshape(orig_shape)
    if bits is None:
        if training and p > 0.0:
            from ...core.generator import default_generator
            bits = jax.random.bits(default_generator().next_key(), (N, H),
                                   jnp.uint32)
        else:
            bits = jnp.zeros((N, H), jnp.uint32)
    out = pl.pallas_call(
        functools.partial(_kernel, p=float(p), eps=float(eps),
                          training=bool(training)),
        grid=(N // R,),
        in_specs=[pl.BlockSpec((R, H), lambda i: (i, 0)),
                  pl.BlockSpec((R, H), lambda i: (i, 0)),
                  pl.BlockSpec((R, H), lambda i: (i, 0)),
                  pl.BlockSpec((H,), lambda i: (0,)),
                  pl.BlockSpec((H,), lambda i: (0,))],
        out_specs=pl.BlockSpec((R, H), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, H), x.dtype),
        interpret=_interpret(),
    )(x2, r2, bits.reshape(N, H), weight, bias)
    return out.reshape(orig_shape)
