"""Fused dropout + residual-add + layernorm Pallas kernel.

~ the reference's fused_bias_dropout_residual_layer_norm family
(paddle/fluid/operators/fused/fused_attention_op.cu,
fused_dropout_helper.h): the transformer residual path
``ln(residual + dropout(x))`` done in one VMEM pass — the three
intermediates never round-trip HBM. Dropout randomness comes in as a
uint32 bits tensor generated with the framework Generator outside the
kernel (seed+offset reproducibility, phi/core/generator.h:23 semantics)
so the kernel itself stays deterministic.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


BLOCK_ROWS = 128


def _kernel(x_ref, res_ref, bits_ref, w_ref, b_ref, o_ref, *, p, eps,
            training):
    x = x_ref[...].astype(jnp.float32)
    if training and p > 0.0:
        # keep when uniform(bits) >= p; inverted scaling keeps E[out]=x
        u = bits_ref[...].astype(jnp.float32) / 4294967296.0
        keep = (u >= p).astype(jnp.float32)
        x = x * keep / (1.0 - p)
    h = x + res_ref[...].astype(jnp.float32)
    mu = jnp.mean(h, axis=-1, keepdims=True)
    hc = h - mu
    var = jnp.mean(hc * hc, axis=-1, keepdims=True)
    y = hc * jax.lax.rsqrt(var + eps)
    y = y * w_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _core(x2, r2, weight, bias, bits, p, eps, training):
    """Differentiable core over flat (N, H) operands.

    Forward is the Pallas kernel; backward is the closed-form layernorm
    gradient (recomputing h/mu/rsig from the saved inputs — cheap
    elementwise work that XLA fuses; the HBM win of the kernel is in the
    forward intermediates)."""
    return _core_fwd(x2, r2, weight, bias, bits, p, eps, training)[0]


def _core_fwd(x2, r2, weight, bias, bits, p, eps, training):
    out = _pallas_forward(x2, r2, weight, bias, bits, p, eps, training)
    return out, (x2, r2, weight, bits)


def _core_bwd(p, eps, training, res, g):
    x2, r2, weight, bits = res
    gf = g.astype(jnp.float32)
    xf = x2.astype(jnp.float32)
    if training and p > 0.0:
        u = bits.astype(jnp.float32) / 4294967296.0
        keep = (u >= p).astype(jnp.float32) / (1.0 - p)
        xf = xf * keep
    else:
        keep = None
    h = xf + r2.astype(jnp.float32)
    mu = h.mean(-1, keepdims=True)
    hc = h - mu
    rsig = jax.lax.rsqrt((hc * hc).mean(-1, keepdims=True) + eps)
    yhat = hc * rsig
    wf = weight.astype(jnp.float32)
    wg = gf * wf
    dh = (wg - wg.mean(-1, keepdims=True)
          - yhat * (wg * yhat).mean(-1, keepdims=True)) * rsig
    dw = jnp.sum(gf * yhat, axis=0).astype(weight.dtype)
    db = jnp.sum(gf, axis=0).astype(weight.dtype)
    dres = dh.astype(r2.dtype)
    dx = (dh * keep if keep is not None else dh).astype(x2.dtype)
    import numpy as np
    dbits = np.zeros(bits.shape, jax.dtypes.float0)
    return dx, dres, dw, db, dbits


_core.defvjp(_core_fwd, _core_bwd)


def fused_dropout_add_layer_norm(x, residual, weight, bias, p=0.1,
                                 eps=1e-5, training=True, bits=None):
    """x, residual: (..., H); weight/bias: (H,). Returns ln(res+drop(x)).

    bits: optional uint32 tensor shaped like x (dropout randomness); when
    None and training, drawn from the framework Generator. Differentiable
    (custom VJP) so it can serve the training-time fused transformer
    layers (incubate/nn), not just inference.
    """
    orig_shape = x.shape
    H = orig_shape[-1]
    x2 = x.reshape(-1, H)
    r2 = residual.reshape(-1, H)
    N = x2.shape[0]
    if bits is None:
        if training and p > 0.0:
            from ...core.generator import default_generator
            bits = jax.random.bits(default_generator().next_key(), (N, H),
                                   jnp.uint32)
        else:
            bits = jnp.zeros((N, H), jnp.uint32)
    out = _core(x2, r2, weight, bias, bits.reshape(N, H),
                float(p), float(eps), bool(training))
    return out.reshape(orig_shape)


def _pallas_forward(x2, r2, weight, bias, bits, p, eps, training):
    N, H = x2.shape
    R = min(BLOCK_ROWS, N)
    if N % R != 0:  # ragged: dense fallback keeps semantics
        xf = x2.astype(jnp.float32)
        if training and p > 0.0:
            u = bits.astype(jnp.float32) / 4294967296.0
            xf = xf * (u >= p).astype(jnp.float32) / (1.0 - p)
        h = xf + r2.astype(jnp.float32)
        mu = h.mean(-1, keepdims=True)
        var = ((h - mu) ** 2).mean(-1, keepdims=True)
        y = (h - mu) * jax.lax.rsqrt(var + eps) * weight + bias
        return y.astype(x2.dtype)
    out = pl.pallas_call(
        functools.partial(_kernel, p=float(p), eps=float(eps),
                          training=bool(training)),
        grid=(N // R,),
        in_specs=[pl.BlockSpec((R, H), lambda i: (i, 0)),
                  pl.BlockSpec((R, H), lambda i: (i, 0)),
                  pl.BlockSpec((R, H), lambda i: (i, 0)),
                  pl.BlockSpec((H,), lambda i: (0,)),
                  pl.BlockSpec((H,), lambda i: (0,))],
        out_specs=pl.BlockSpec((R, H), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, H), x2.dtype),
        interpret=_interpret(),
    )(x2, r2, bits, weight, bias)
    return out
