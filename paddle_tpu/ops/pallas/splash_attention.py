"""Block-sparse ("splash") flash attention Pallas TPU kernel.

~ the reference's sparse_attention_op.cu (block-sparse SDD attention over
a CSR pattern) — which computes DENSE scores and masks. Here masked-out
blocks are truly SKIPPED in the forward and dQ walks: a per-q-block list
of live kv-block indices (scalar-prefetched into SMEM) drives the
online-softmax walk, so compute and VMEM traffic scale with the
pattern's density, not O(S^2). Same resident-KV + exp2-domain design as
flash_attention.py.

ONE kernel family serves both MHA and GQA/MQA: queries carry a group
dimension (the G query heads sharing a kv head fold into the matmul M
dimension, K/V stay at their true head count — flash_attention_gqa.py's
layout); plain multi-head attention is the G=1 case. The dK/dV backward
STREAMS q blocks through an innermost grid dimension with VMEM scratch
accumulators (full-sequence q/do residency would be G*Sq*D — over VMEM
at training shapes); dead (q, kv) block pairs skip their compute via a
prefetched block-mask predicate (their DMA still runs — Mosaic fetches
per grid step — so the dkv pass is DMA-dense but compute-sparse).

The block pattern is a (num_q_blocks, num_kv_blocks) bool mask — the
natural TPU granularity (MXU tiles), and the form local/strided/BigBird
patterns compress to. ``causal=True`` applies the elementwise triangle
inside live blocks; ``window`` additionally applies the token-exact
sliding-window band (q_pos - k_pos < window, Mistral semantics).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...jax_compat import tpu_compiler_params

# jax renamed TPUCompilerParams -> CompilerParams (version-bridged in
# one place, jax_compat)
_CompilerParams = tpu_compiler_params()

from .flash_attention import LN2, LOG2E, NEG_INF, _interpret

# f32-element budget for one (G*block_q, block_k) score/probability buffer
# (2 MB each); _resolve raises when a grouped config exceeds it
SCORE_ELEMS = 512 * 1024


# see flash_attention_gqa._MAX_ROWS — same v5e scoped-vmem measurement
MAX_ROWS = 2048

# Resident K/V in the fwd/dq kernels grows with Sk (the long8k failure
# mode of the MHA flash kernels); past this frontier the kernels switch
# to STREAMING live kv blocks through an innermost grid dimension whose
# index map reads the prefetched kv_idx table — VMEM drops to O(block)
# and DMA to O(live blocks), i.e. the pattern's density (the resident
# walk DMAs nothing per step but holds all of K/V; the dkv pass was
# always streamed). The fit model is flash_attention_gqa's — one
# definition, recalibrated in one place by tools/long8k_vmem_repro.py.
# None = automatic; tests/benches may force a mode.
from .flash_attention_gqa import _gqa_fits as _resident_fits  # noqa: E402

_FORCE_STREAM = None


def fits_score_budget(groups: int, block_q: int = 128,
                      block_k: int = 128) -> bool:
    """The kernel's VMEM eligibility predicate — ONE definition shared
    with model-level gates (llama's grouped sliding-window path) so the
    bound can't drift between the kernel and its callers. Checks both
    the (G*bq, bk) score-buffer budget and the G*bq row cap (rows-tall
    q/acc/out buffers bound VMEM independently of block_k)."""
    return (groups * block_q * block_k <= SCORE_ELEMS
            and groups * block_q <= MAX_ROWS)


def pick_splash_blocks(Sq: int, Sk: int, groups: int = 1):
    """Largest square block pair (512 -> 256 -> 128) that divides the
    sequences and fits the score/row budgets. Measured on v5e
    (2026-08-01, fwd+bwd chains): at window=2048/S=8192 the 512-block
    banded kernel runs 20.4 ms vs 62.0 ms at 128 blocks — per-block
    overhead dominates the extra boundary density at every window down
    to 256 — so callers building masks should use the coarsest tiling
    the budgets allow, not the finest."""
    for cand in (512, 256, 128):
        if Sq % cand or Sk % cand:
            continue
        bq = bk = cand
        while not fits_score_budget(groups, bq, bk) and bk > 128:
            bk //= 2
        # large groups (MQA) blow the G*bq row cap at any bk: shrink bq
        # (halving preserves divisibility and sublane alignment down to 8)
        while not fits_score_budget(groups, bq, bk) and bq > 8 \
                and (bq // 2) % 8 == 0:
            bq //= 2
        if fits_score_budget(groups, bq, bk):
            return bq, bk
    return 128, 128


def _pattern_tables(block_mask: np.ndarray):
    """Dense (nq, nk) bool -> padded per-q-block kv index lists.

    Returns (kv_idx (nq, max_kv), kv_cnt (nq,)) int32; padding entries
    repeat the last valid index (never walked — counts bound the
    fori_loop)."""
    bm = np.asarray(block_mask, bool)
    nq, _ = bm.shape
    kv_cnt = bm.sum(1).astype(np.int32)
    max_kv = max(1, int(kv_cnt.max()))
    kv_idx = np.zeros((nq, max_kv), np.int32)
    for i in range(nq):
        live = np.flatnonzero(bm[i])
        kv_idx[i, :len(live)] = live
        if len(live):
            kv_idx[i, len(live):] = live[-1]
    return kv_idx, kv_cnt


def banded_block_mask(Sq, Sk, block_q, block_k, window,
                      causal=True) -> np.ndarray:
    """Block mask for sliding-window attention: block (i, j) is live iff
    some (q_pos, k_pos) pair in it satisfies the causal triangle and
    q_pos - k_pos < window (token-exact masking happens in-kernel)."""
    nq, nk = Sq // block_q, Sk // block_k
    bm = np.zeros((nq, nk), bool)
    for i in range(nq):
        q_hi = (i + 1) * block_q - 1
        q_lo = i * block_q
        for j in range(nk):
            k_hi = (j + 1) * block_k - 1
            k_lo = j * block_k
            if causal and k_lo > q_hi:
                continue
            # the block's MINIMUM q_pos - k_pos is q_lo - k_hi; the block
            # is dead only when even that violates the band
            if window is not None and q_lo - k_hi >= window:
                continue
            bm[i, j] = True
    return bm


def _live_mask(qi, kj, rows, block_q, block_k, causal, window,
               q_offset=0):
    """Elementwise live mask for a (G*block_q, block_k) score block: row
    r belongs to query position qi*block_q + (r % block_q) — the group
    index r // block_q shares positions across the G heads. q_offset
    shifts the query frame relative to the keys (ring attention's
    cross-chunk pairs: chunk distance d puts queries d*S_local ahead of
    the held K/V chunk)."""
    r = jax.lax.broadcasted_iota(jnp.int32, (rows, block_k), 0)
    q_pos = q_offset + qi * block_q + jax.lax.rem(r, block_q)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (rows, block_k), 1)
    live = jnp.ones((rows, block_k), bool)
    if causal:
        live &= q_pos >= k_pos
    if window is not None:
        live &= (q_pos - k_pos) < window
    return live


def _fwd_kernel(kv_idx, kv_cnt, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                sm_scale, causal, block_q, block_k, window, groups,
                q_offset=0):
    qi = pl.program_id(1)
    G = groups
    D = q_ref.shape[-1]
    rows = G * block_q
    q = q_ref[0].reshape(rows, D)
    q2 = (q.astype(jnp.float32) * (sm_scale * LOG2E)).astype(q.dtype)
    m = jnp.full((rows,), NEG_INF, jnp.float32)
    l = jnp.zeros((rows,), jnp.float32)
    acc = jnp.zeros((rows, D), jnp.float32)

    def body(t, carry):
        m, l, acc = carry
        kj = kv_idx[qi, t]
        k = k_ref[0, pl.dslice(kj * block_k, block_k)]
        v = v_ref[0, pl.dslice(kj * block_k, block_k)]
        s = jax.lax.dot_general(q2, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal or window is not None:
            s = jnp.where(_live_mask(qi, kj, rows, block_q, block_k,
                                     causal, window,
                                     q_offset), s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp2(s - m_new[:, None])
        # rows with NO live entry yet (m_new still NEG_INF — e.g. a live
        # block entirely above the causal diagonal): exp2(s - m_new) is
        # exp2(0) = 1 per entry since NEG_INF is finite; zero them so
        # such rows accumulate no bogus mass
        p = jnp.where((m_new > NEG_INF * 0.5)[:, None], p, 0.0)
        alpha = jnp.exp2(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, kv_cnt[qi], body, (m, l, acc))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    # fully-masked rows (no live block, or live blocks fully above the
    # causal diagonal) output 0
    any_mass = l > 0.0
    o_ref[0] = jnp.where(any_mass[:, None], acc / l_safe[:, None],
                         0.0).reshape(G, block_q, D).astype(o_ref.dtype)
    lse_ref[0] = jnp.where(any_mass, LN2 * m + jnp.log(l_safe),
                           NEG_INF).reshape(G, block_q, 1).astype(
        jnp.float32)


def _bwd_dq_kernel(kv_idx, kv_cnt, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, *, sm_scale, causal, block_q,
                   block_k, window, groups, q_offset=0):
    qi = pl.program_id(1)
    G = groups
    D = q_ref.shape[-1]
    rows = G * block_q
    q = q_ref[0].reshape(rows, D)
    q2 = (q.astype(jnp.float32) * (sm_scale * LOG2E)).astype(q.dtype)
    do = do_ref[0].reshape(rows, D)
    lse2 = lse_ref[0].reshape(rows) * LOG2E
    delta = delta_ref[0].reshape(rows)
    dq = jnp.zeros((rows, D), jnp.float32)

    def body(t, dq):
        kj = kv_idx[qi, t]
        k = k_ref[0, pl.dslice(kj * block_k, block_k)]
        v = v_ref[0, pl.dslice(kj * block_k, block_k)]
        s = jax.lax.dot_general(q2, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal or window is not None:
            s = jnp.where(_live_mask(qi, kj, rows, block_q, block_k,
                                     causal, window,
                                     q_offset), s, NEG_INF)
        # masked entries must be 0 regardless of lse: for an all-masked
        # row lse is NEG_INF and s - lse2 would OVERFLOW to +inf
        p = jnp.where(s > NEG_INF * 0.5, jnp.exp2(s - lse2[:, None]), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        return dq + jax.lax.dot_general(ds.astype(k.dtype), k,
                                        (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, kv_cnt[qi], body, dq)
    dq_ref[0] = dq.reshape(G, block_q, D).astype(dq_ref.dtype)


def _fwd_kernel_stream(kv_idx, kv_cnt, q_ref, k_ref, v_ref, o_ref,
                       lse_ref, m_scr, l_scr, acc_scr, *, sm_scale,
                       causal, block_q, block_k, window, groups, t_max,
                       q_offset=0):
    """Forward with LIVE kv blocks streamed through the innermost grid
    dimension: the k/v BlockSpec index maps read kv_idx[qi, t] from the
    scalar-prefetch channel, so only live blocks are ever DMA'd and VMEM
    holds one block — no resident K/V, no S ceiling. Same online-softmax
    math as `_fwd_kernel`, with the (m, l, acc) carry in VMEM scratch."""
    qi = pl.program_id(1)
    t = pl.program_id(2)
    G = groups
    D = q_ref.shape[-1]
    rows = G * block_q

    @pl.when(t == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    @pl.when(t < kv_cnt[qi])
    def _compute():
        kj = kv_idx[qi, t]
        q = q_ref[0].reshape(rows, D)
        q2 = (q.astype(jnp.float32) * (sm_scale * LOG2E)).astype(q.dtype)
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q2, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal or window is not None:
            s = jnp.where(_live_mask(qi, kj, rows, block_q, block_k,
                                     causal, window,
                                     q_offset), s, NEG_INF)
        m = m_scr[...][:, 0]
        l = l_scr[...][:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp2(s - m_new[:, None])
        p = jnp.where((m_new > NEG_INF * 0.5)[:, None], p, 0.0)
        alpha = jnp.exp2(m - m_new)
        l_scr[...] = (alpha * l + jnp.sum(p, axis=1))[:, None]
        acc_scr[...] = acc_scr[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = m_new[:, None]

    @pl.when(t == t_max - 1)
    def _flush():
        m = m_scr[...][:, 0]
        l = l_scr[...][:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        any_mass = l > 0.0
        o_ref[0] = jnp.where(
            any_mass[:, None], acc_scr[...] / l_safe[:, None],
            0.0).reshape(G, block_q, D).astype(o_ref.dtype)
        lse_ref[0] = jnp.where(any_mass, LN2 * m + jnp.log(l_safe),
                               NEG_INF).reshape(G, block_q, 1).astype(
            jnp.float32)


def _bwd_dq_kernel_stream(kv_idx, kv_cnt, q_ref, k_ref, v_ref, do_ref,
                          lse_ref, delta_ref, dq_ref, dq_scr, *,
                          sm_scale, causal, block_q, block_k, window,
                          groups, t_max, q_offset=0):
    qi = pl.program_id(1)
    t = pl.program_id(2)
    G = groups
    D = q_ref.shape[-1]
    rows = G * block_q

    @pl.when(t == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    @pl.when(t < kv_cnt[qi])
    def _compute():
        kj = kv_idx[qi, t]
        q = q_ref[0].reshape(rows, D)
        q2 = (q.astype(jnp.float32) * (sm_scale * LOG2E)).astype(q.dtype)
        do = do_ref[0].reshape(rows, D)
        lse2 = lse_ref[0].reshape(rows) * LOG2E
        delta = delta_ref[0].reshape(rows)
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q2, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal or window is not None:
            s = jnp.where(_live_mask(qi, kj, rows, block_q, block_k,
                                     causal, window,
                                     q_offset), s, NEG_INF)
        p = jnp.where(s > NEG_INF * 0.5, jnp.exp2(s - lse2[:, None]), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dq_scr[...] = dq_scr[...] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == t_max - 1)
    def _flush():
        dq_ref[0] = dq_scr[...].reshape(G, block_q, D).astype(
            dq_ref.dtype)


def _bwd_dkv_kernel(bm_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                    sm_scale, causal, block_q, block_k, window, groups,
                    num_q, q_offset=0):
    """dK/dV with q blocks STREAMED through the innermost grid dimension
    (VMEM holds one (G, bq, D) q/do block, not the sequence); compute for
    dead (q, kv) pairs is skipped via the prefetched block-mask
    predicate."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    G = groups
    D = q_ref.shape[-1]
    rows = G * block_q

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    @pl.when(bm_ref[qi, kj] > 0)
    def _compute():
        k = k_ref[0]
        v = v_ref[0]
        k2 = (k.astype(jnp.float32) * (sm_scale * LOG2E)).astype(k.dtype)
        q = q_ref[0].reshape(rows, D)
        do = do_ref[0].reshape(rows, D)
        lse2 = lse_ref[0].reshape(rows) * LOG2E
        delta = delta_ref[0].reshape(rows)
        s = jax.lax.dot_general(q, k2, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal or window is not None:
            s = jnp.where(_live_mask(qi, kj, rows, block_q, block_k,
                                     causal, window,
                                     q_offset), s, NEG_INF)
        # same NEG_INF-lse guard as the dq kernel
        p = jnp.where(s > NEG_INF * 0.5, jnp.exp2(s - lse2[:, None]), 0.0)
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q - 1)
    def _flush():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _resolve(q, k, block_mask, sm_scale, block_q, block_k):
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    nq, nk = np.asarray(block_mask).shape
    bq = block_q or q.shape[2] // nq
    bk = block_k or k.shape[2] // nk
    if q.shape[2] != nq * bq or k.shape[2] != nk * bk:
        raise ValueError(
            f"splash_attention: block_mask {nq}x{nk} with blocks "
            f"({bq},{bk}) does not tile seqs ({q.shape[2]},{k.shape[2]})")
    if q.shape[1] % max(1, k.shape[1]):
        raise ValueError(
            f"query heads {q.shape[1]} not a multiple of kv heads "
            f"{k.shape[1]}")
    G = q.shape[1] // max(1, k.shape[1])
    if not fits_score_budget(G, bq, bk):
        # rows-tall (G*bq) q/acc/out buffers bound VMEM independently of
        # bk: measured on v5e, rows=4096 exceeds the 16M scoped-vmem
        # limit by ~1M even with the score budget satisfied (see
        # flash_attention_gqa._MAX_ROWS). Splash blocks are pinned by
        # the mask tiling, so the fix is a clear error, not auto-shrink.
        if G * bq > MAX_ROWS:
            raise ValueError(
                f"splash_attention: G*block_q = {G * bq} rows exceeds "
                f"the VMEM row budget ({MAX_ROWS}); use a finer "
                f"block_mask granularity (smaller block_q) or fewer "
                f"query groups")
        raise ValueError(
            f"splash_attention: G*block_q*block_k = {G * bq * bk} f32 "
            f"elements exceeds the VMEM score budget ({SCORE_ELEMS}); "
            f"use a finer block_mask granularity"
            + (" or repeat K/V across fewer query groups" if G > 1
               else ""))
    if _FORCE_STREAM is not None:
        streamed = _FORCE_STREAM
    else:
        streamed = not _resident_fits(G * bq, bk, k.shape[2],
                                      q.shape[-1], q.dtype.itemsize)
    return sm_scale, bq, bk, G, streamed


def _splash_fwd(q, k, v, block_mask, causal, sm_scale, block_q, block_k,
                window=None, q_offset=0):
    sm_scale, bq, bk, G, streamed = _resolve(q, k, block_mask, sm_scale,
                                             block_q, block_k)
    kv_idx, kv_cnt = _pattern_tables(block_mask)
    B, Hq, Sq, D = q.shape
    Hkv = k.shape[1]
    Sk = k.shape[2]
    bh = B * Hkv
    qr = q.reshape(bh, G, Sq, D)
    kr = k.reshape(bh, Sk, D)
    vr = v.reshape(bh, Sk, D)
    if streamed:
        t_max = kv_idx.shape[1]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, Sq // bq, t_max),
            in_specs=[
                pl.BlockSpec((1, G, bq, D),
                             lambda b, i, t, idx, cnt: (b, 0, i, 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, i, t, idx, cnt: (b, idx[i, t], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, i, t, idx, cnt: (b, idx[i, t], 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, G, bq, D),
                             lambda b, i, t, idx, cnt: (b, 0, i, 0)),
                pl.BlockSpec((1, G, bq, 1),
                             lambda b, i, t, idx, cnt: (b, 0, i, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((G * bq, 1), jnp.float32),
                pltpu.VMEM((G * bq, 1), jnp.float32),
                pltpu.VMEM((G * bq, D), jnp.float32),
            ],
        )
        kernel = functools.partial(
            _fwd_kernel_stream, sm_scale=sm_scale, causal=causal,
            block_q=bq, block_k=bk, window=window, groups=G, t_max=t_max,
            q_offset=q_offset)
        semantics = ("parallel", "parallel", "arbitrary")
    else:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, Sq // bq),
            in_specs=[
                pl.BlockSpec((1, G, bq, D), lambda b, i, *_: (b, 0, i, 0)),
                pl.BlockSpec((1, Sk, D), lambda b, i, *_: (b, 0, 0)),
                pl.BlockSpec((1, Sk, D), lambda b, i, *_: (b, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, G, bq, D), lambda b, i, *_: (b, 0, i, 0)),
                pl.BlockSpec((1, G, bq, 1), lambda b, i, *_: (b, 0, i, 0)),
            ],
        )
        kernel = functools.partial(
            _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=bq,
            block_k=bk, window=window, groups=G, q_offset=q_offset)
        semantics = ("parallel", "arbitrary")
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, G, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((bh, G, Sq, 1), jnp.float32),
        ],
        interpret=_interpret(),
        compiler_params=_CompilerParams(
            dimension_semantics=semantics),
    )(jnp.asarray(kv_idx), jnp.asarray(kv_cnt), qr, kr, vr)
    out = out.reshape(B, Hq, Sq, D)
    return out, (q, k, v, out, lse.reshape(B, Hq, Sq))


def _splash_bwd(block_mask, causal, sm_scale, block_q, block_k, window,
                q_offset, res, do, *, delta=None):
    # delta: optional precomputed sum(dO*O, -1) as (B,H,Sq) f32 — ring
    # attention calls this once per ring step with the same global
    # (out, dO), so the reduction hoists out of the ring loop
    # (mirrors flash_attention._fa_bwd's delta kwarg)
    q, k, v, out, lse = res
    sm_scale, bq, bk, G, streamed = _resolve(q, k, block_mask, sm_scale,
                                             block_q, block_k)
    kv_idx, kv_cnt = _pattern_tables(block_mask)
    B, Hq, Sq, D = q.shape
    Hkv = k.shape[1]
    Sk = k.shape[2]
    bh = B * Hkv
    qr = q.reshape(bh, G, Sq, D)
    kr = k.reshape(bh, Sk, D)
    vr = v.reshape(bh, Sk, D)
    dor = do.reshape(bh, G, Sq, D)
    lser = lse.reshape(bh, G, Sq, 1)
    if delta is None:
        delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1)
    delta = delta.reshape(bh, G, Sq, 1)

    if streamed:
        t_max = kv_idx.shape[1]
        dq_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, Sq // bq, t_max),
            in_specs=[
                pl.BlockSpec((1, G, bq, D),
                             lambda b, i, t, idx, cnt: (b, 0, i, 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, i, t, idx, cnt: (b, idx[i, t], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, i, t, idx, cnt: (b, idx[i, t], 0)),
                pl.BlockSpec((1, G, bq, D),
                             lambda b, i, t, idx, cnt: (b, 0, i, 0)),
                pl.BlockSpec((1, G, bq, 1),
                             lambda b, i, t, idx, cnt: (b, 0, i, 0)),
                pl.BlockSpec((1, G, bq, 1),
                             lambda b, i, t, idx, cnt: (b, 0, i, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, G, bq, D), lambda b, i, t, idx, cnt: (b, 0, i, 0)),
            scratch_shapes=[pltpu.VMEM((G * bq, D), jnp.float32)],
        )
        dq_kernel = functools.partial(
            _bwd_dq_kernel_stream, sm_scale=sm_scale, causal=causal,
            block_q=bq, block_k=bk, window=window, groups=G, t_max=t_max,
            q_offset=q_offset)
        dq_semantics = ("parallel", "parallel", "arbitrary")
    else:
        dq_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, Sq // bq),
            in_specs=[
                pl.BlockSpec((1, G, bq, D), lambda b, i, *_: (b, 0, i, 0)),
                pl.BlockSpec((1, Sk, D), lambda b, i, *_: (b, 0, 0)),
                pl.BlockSpec((1, Sk, D), lambda b, i, *_: (b, 0, 0)),
                pl.BlockSpec((1, G, bq, D), lambda b, i, *_: (b, 0, i, 0)),
                pl.BlockSpec((1, G, bq, 1), lambda b, i, *_: (b, 0, i, 0)),
                pl.BlockSpec((1, G, bq, 1), lambda b, i, *_: (b, 0, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, G, bq, D),
                                   lambda b, i, *_: (b, 0, i, 0)),
        )
        dq_kernel = functools.partial(
            _bwd_dq_kernel, sm_scale=sm_scale, causal=causal, block_q=bq,
            block_k=bk, window=window, groups=G, q_offset=q_offset)
        dq_semantics = ("parallel", "arbitrary")
    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct((bh, G, Sq, D), q.dtype),
        interpret=_interpret(),
        compiler_params=_CompilerParams(
            dimension_semantics=dq_semantics),
    )(jnp.asarray(kv_idx), jnp.asarray(kv_cnt), qr, kr, vr, dor, lser,
      delta)

    num_q = Sq // bq
    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, Sk // bk, num_q),
        in_specs=[
            pl.BlockSpec((1, G, bq, D), lambda b, j, i, *_: (b, 0, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i, *_: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i, *_: (b, j, 0)),
            pl.BlockSpec((1, G, bq, D), lambda b, j, i, *_: (b, 0, i, 0)),
            pl.BlockSpec((1, G, bq, 1), lambda b, j, i, *_: (b, 0, i, 0)),
            pl.BlockSpec((1, G, bq, 1), lambda b, j, i, *_: (b, 0, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i, *_: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i, *_: (b, j, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
    )
    bm_i32 = jnp.asarray(np.asarray(block_mask, np.int32))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=bq, block_k=bk,
                          window=window, groups=G, num_q=num_q,
                          q_offset=q_offset),
        grid_spec=dkv_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((bh, Sk, D), v.dtype),
        ],
        interpret=_interpret(),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(bm_i32, qr, kr, vr, dor, lser, delta)

    return (dq.reshape(B, Hq, Sq, D), dk.reshape(B, Hkv, Sk, D),
            dv.reshape(B, Hkv, Sk, D))


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def splash_attention(q, k, v, block_mask, causal=False, sm_scale=None,
                     block_q=None, block_k=None, window=None,
                     q_offset=0):
    """q: (B, Hq, S, D); k/v: (B, Hkv, S, D) with Hq a multiple of Hkv
    (MHA is Hq == Hkv; GQA/MQA fold the group into the kernel's M dim).
    block_mask: (Sq//block_q, Sk//block_k) bool numpy array (a static
    pattern — it defines the compiled kernel). Equivalent to dense
    attention with masked-out blocks at -inf, but skipped rather than
    computed."""
    out, _ = _splash_fwd(q, k, v, block_mask, causal, sm_scale, block_q,
                         block_k, window, q_offset)
    return out


splash_attention.defvjp(_splash_fwd, _splash_bwd)

# GQA entry point: same kernel family; kept as a named alias so call
# sites read as grouped (and for parity with flash_attention_gqa.py)
grouped_splash_attention = splash_attention
