"""Fused LayerNorm / RMSNorm Pallas kernels.

Replaces the reference's layer_norm CUDA kernel
(paddle/phi/kernels/gpu/layer_norm_kernel.cu) and the fused
bias+dropout+residual+LN of fused_attention. One VMEM pass per row block:
load, reduce, normalize, scale — no intermediate HBM round trips. Stats in
f32 regardless of input dtype (bf16-safe).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    # CPU backend (tests / sim meshes) runs kernels in interpreter mode
    import jax
    return jax.default_backend() == "cpu"

BLOCK_ROWS = 256


def _ln_kernel(x_ref, w_ref, b_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    y = y * w_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


def _rms_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(ms + eps)
    y = y * w_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


def _rows_block(n_rows):
    for cand in (BLOCK_ROWS, 128, 64, 32, 16, 8, 4, 2, 1):
        if n_rows % cand == 0:
            return cand
    return 1


@functools.partial(jax.jit, static_argnames=("eps",))
def fused_layer_norm(x, weight, bias, eps=1e-5):
    """x: (..., hidden). weight/bias: (hidden,)."""
    shape = x.shape
    H = shape[-1]
    xr = x.reshape(-1, H)
    R = xr.shape[0]
    br = _rows_block(R)
    out = functools.partial(pl.pallas_call, interpret=_interpret())(
        functools.partial(_ln_kernel, eps=eps),
        grid=(R // br,),
        in_specs=[
            pl.BlockSpec((br, H), lambda i: (i, 0)),
            pl.BlockSpec((H,), lambda i: (0,)),
            pl.BlockSpec((H,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((br, H), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((R, H), x.dtype),
    )(xr, weight, bias)
    return out.reshape(shape)


@functools.partial(jax.jit, static_argnames=("eps",))
def fused_rms_norm(x, weight, eps=1e-6):
    shape = x.shape
    H = shape[-1]
    xr = x.reshape(-1, H)
    R = xr.shape[0]
    br = _rows_block(R)
    out = functools.partial(pl.pallas_call, interpret=_interpret())(
        functools.partial(_rms_kernel, eps=eps),
        grid=(R // br,),
        in_specs=[
            pl.BlockSpec((br, H), lambda i: (i, 0)),
            pl.BlockSpec((H,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((br, H), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((R, H), x.dtype),
    )(xr, weight)
    return out.reshape(shape)
