"""TensorArray ops.

~ python/paddle/tensor/array.py (create_array/array_write/array_read/
array_length over LoDTensorArray; fluid/operators/array_operator.h). TPU
lowering: eagerly a TensorArray IS a Python list of Tensors — there is no
LoDTensorArray runtime object to mirror because XLA has no dynamic-length
containers; compiled loops express accumulation as `lax.scan`/stacked
buffers via the train-step factories instead. Indices accept Python ints
or scalar int Tensors (the reference's fill_constant counters).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["create_array", "array_write", "array_read", "array_length"]


def _index(i) -> int:
    if isinstance(i, Tensor):
        return int(i._value)
    return int(i)


def create_array(dtype: str = "float32", initialized_list=None) -> list:
    """~ paddle.tensor.create_array: a new TensorArray, optionally seeded
    with ``initialized_list``. ``dtype`` is accepted for API parity (the
    eager list is heterogeneous-tolerant like the reference's dygraph
    path)."""
    out = []
    if initialized_list is not None:
        out.extend(initialized_list)
    return out


def array_write(x, i, array: list | None = None) -> list:
    """~ paddle.tensor.array_write: write ``x`` at index ``i``; appends
    when ``i == len(array)`` (the common increment-counter pattern)."""
    if array is None:
        array = []
    idx = _index(i)
    if idx > len(array):
        raise IndexError(
            f"array_write index {idx} beyond array length {len(array)}")
    x = x if isinstance(x, Tensor) else Tensor(x)
    if idx == len(array):
        array.append(x)
    else:
        array[idx] = x
    return array


def array_read(array: list, i):
    """~ paddle.tensor.array_read."""
    return array[_index(i)]


def array_length(array: list) -> Tensor:
    """~ paddle.tensor.array_length: int64 scalar length (int32 when x64
    is disabled — the repo-wide truncation convention)."""
    import jax
    t = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    return Tensor(jnp.asarray(len(array), t))
