"""Op layer: jax-lowered eager ops with tape autograd.

Registry + dispatch (dispatch.py) ~ phi::KernelFactory; the modules here are
the kernel families (paddle/phi/kernels/*) re-expressed as jax lowerings.
"""
from .dispatch import OP_REGISTRY, apply_op, def_op  # noqa: F401
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .reduction import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .activation import *  # noqa: F401,F403
from .misc import *  # noqa: F401,F403
from .array_ops import *  # noqa: F401,F403
from . import tensor_methods as _tm
from . import codegen as _codegen
from .codegen import infer_meta  # noqa: F401

# family groups are generated inside their modules (imported above via *)
_generated_ops = _codegen.generate(
    globals(), exclude_groups={"math", "activation"})
_tm.install()
