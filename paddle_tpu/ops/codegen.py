"""YAML op-spec code generation.

~ the reference's build-time codegen (python/paddle/utils/code_gen/
api_gen.py over api.yaml, emitting the C++ API + kernel dispatch calls,
api_base.py:735). Here generation happens at import: each YAML entry
becomes a registered eager op (ops/specs.yaml). Backward rules need no
backward.yaml — the dispatcher derives VJPs; infermeta is jax.eval_shape.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List

import jax
import jax.numpy as jnp

from .dispatch import OP_REGISTRY, apply_op

_SPEC_PATH = os.path.join(os.path.dirname(__file__), "specs.yaml")


def _compile_lowering(expr: str):
    """'x, y=1 -> body' lambda spec, or a dotted callable path such as
    'jnp.add' / 'jax.lax.rsqrt' -> python function over jax values."""
    if "->" not in expr:
        root, *attrs = expr.strip().split(".")
        obj = {"jnp": jnp, "jax": jax}[root]
        for a in attrs:
            obj = getattr(obj, a)
        return obj
    sig, body = expr.split("->", 1)
    src = f"lambda {sig.strip()}: {body.strip()}"
    return eval(src, {"jnp": jnp, "jax": jax})  # noqa: S307 (trusted spec)


def _parse_attr(s: str):
    name, default = s.split("=", 1)
    return name.strip(), eval(default, {})  # noqa: S307


_SPEC_CACHE: Dict[str, List[Dict[str, Any]]] = {}


def load_specs(path: str = _SPEC_PATH) -> List[Dict[str, Any]]:
    # cached: the YAML is parsed once even though ops/math.py and
    # ops/__init__.py both generate (different groups) at import
    if path not in _SPEC_CACHE:
        import yaml
        with open(path) as f:
            _SPEC_CACHE[path] = yaml.safe_load(f)
    return _SPEC_CACHE[path]


def generate(namespace: dict, path: str = _SPEC_PATH, groups=None,
             exclude_groups=None) -> List[str]:
    """Create API functions for spec entries; returns generated names.

    ``groups``/``exclude_groups`` filter on each spec's ``group`` field
    (default group: "misc") so kernel-family modules (ops/math.py) can own
    their sections of the YAML while ops/__init__ generates the rest —
    mirroring the reference's per-family api.yaml organisation.
    """
    names = []
    for spec in load_specs(path):
        g = spec.get("group", "misc")
        if groups is not None and g not in groups:
            continue
        if exclude_groups is not None and g in exclude_groups:
            continue
        opname = spec["op"]
        fn = _compile_lowering(spec["lowering"])
        nondiff = bool(spec.get("nondiff", False))
        attrs = dict(_parse_attr(a) for a in spec.get("attrs", []))
        n_args = len(spec.get("args", []))

        def make_api(opname=opname, fn=fn, nondiff=nondiff, attrs=attrs,
                     n_args=n_args):
            attr_names = list(attrs)

            def api(*args, **kwargs):
                if len(args) > n_args + len(attr_names):
                    raise TypeError(
                        f"{opname}() takes at most "
                        f"{n_args + len(attr_names)} positional argument(s) "
                        f"but {len(args)} were given")
                merged = dict(attrs)
                # attrs may be passed positionally after the tensor args
                # (matching the reference signatures, e.g.
                # leaky_relu(x, 0.1))
                for name, val in zip(attr_names, args[n_args:]):
                    if name in kwargs:
                        raise TypeError(
                            f"{opname}() got multiple values for "
                            f"argument '{name}'")
                    merged[name] = val
                merged.update(kwargs)
                return apply_op(opname, fn, *args[:n_args], nondiff=nondiff,
                                **merged)
            api.__name__ = opname
            api.op_name = opname
            api.raw_fn = fn
            return api

        api = make_api()
        OP_REGISTRY[opname] = api
        namespace[opname] = api
        names.append(opname)
    return names


def infer_meta(op_name: str, *arg_specs, **attrs):
    """Shape/dtype inference without execution (~ phi infermeta /
    MetaTensor): jax.eval_shape over the registered lowering.

    arg_specs: jax.ShapeDtypeStruct / arrays / Tensors.
    """
    from ..core.tensor import Tensor
    api = OP_REGISTRY.get(op_name)
    if api is None or not hasattr(api, "raw_fn"):
        raise KeyError(f"no registered lowering for op {op_name!r}")

    def to_spec(a):
        if isinstance(a, Tensor):
            return jax.ShapeDtypeStruct(tuple(a.shape), a._value.dtype)
        if isinstance(a, jax.ShapeDtypeStruct):
            return a
        arr = jnp.asarray(a)
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

    specs = [to_spec(a) for a in arg_specs]
    return jax.eval_shape(lambda *xs: api.raw_fn(*xs, **attrs), *specs)
