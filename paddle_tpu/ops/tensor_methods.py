"""Attach operator dunders and tensor methods onto Tensor.

~ the monkey-patching the reference does in
python/paddle/fluid/dygraph/math_op_patch.py + varbase_patch_methods.py:
every `paddle.X(x, ...)` op with a tensor first-arg becomes `x.X(...)`.
"""
from __future__ import annotations

from ..core.tensor import Tensor
from . import activation, creation, linalg, manipulation, math, reduction


def _attach(name, fn):
    if not hasattr(Tensor, name):
        setattr(Tensor, name, fn)


def _rbin(fn):
    def method(self, other):
        return fn(other if isinstance(other, Tensor) else Tensor(other), self)
    return method


def install():
    T = Tensor
    # arithmetic dunders
    T.__add__ = lambda s, o: math.add(s, o)
    T.__radd__ = _rbin(math.add)
    T.__sub__ = lambda s, o: math.subtract(s, o)
    T.__rsub__ = _rbin(math.subtract)
    T.__mul__ = lambda s, o: math.multiply(s, o)
    T.__rmul__ = _rbin(math.multiply)
    T.__truediv__ = lambda s, o: math.divide(s, o)
    T.__rtruediv__ = _rbin(math.divide)
    T.__floordiv__ = lambda s, o: math.floor_divide(s, o)
    T.__rfloordiv__ = _rbin(math.floor_divide)
    T.__mod__ = lambda s, o: math.mod(s, o)
    T.__rmod__ = _rbin(math.mod)
    T.__pow__ = lambda s, o: math.pow_(s, o)
    T.__rpow__ = _rbin(math.pow_)
    T.__neg__ = lambda s: math.neg(s)
    T.__abs__ = lambda s: math.abs(s)
    T.__matmul__ = lambda s, o: linalg.matmul(s, o)
    T.__rmatmul__ = _rbin(linalg.matmul)
    # comparison dunders
    T.__eq__ = lambda s, o: math.equal(s, o)
    T.__ne__ = lambda s, o: math.not_equal(s, o)
    T.__lt__ = lambda s, o: math.less_than(s, o)
    T.__le__ = lambda s, o: math.less_equal(s, o)
    T.__gt__ = lambda s, o: math.greater_than(s, o)
    T.__ge__ = lambda s, o: math.greater_equal(s, o)
    T.__invert__ = lambda s: math.logical_not(s)
    T.__and__ = lambda s, o: math.logical_and(s, o) \
        if s.dtype == bool else math.bitwise_and(s, o)
    T.__or__ = lambda s, o: math.logical_or(s, o) \
        if s.dtype == bool else math.bitwise_or(s, o)
    T.__xor__ = lambda s, o: math.logical_xor(s, o) \
        if s.dtype == bool else math.bitwise_xor(s, o)

    # indexing: functional gather/setitem
    def _getitem(self, idx):
        from .dispatch import apply_op

        def unwrap_idx(i):
            if isinstance(i, Tensor):
                return i._value
            if isinstance(i, tuple):
                return tuple(unwrap_idx(e) for e in i)
            if isinstance(i, list):
                return [unwrap_idx(e) for e in i]
            return i
        j = unwrap_idx(idx)
        return apply_op("getitem", lambda v: v[j], self)

    def _setitem(self, idx, value):
        import jax.numpy as jnp

        def unwrap_idx(i):
            if isinstance(i, Tensor):
                return i._value
            if isinstance(i, tuple):
                return tuple(unwrap_idx(e) for e in i)
            return i
        j = unwrap_idx(idx)
        v = value._value if isinstance(value, Tensor) else value
        self._value = self._value.at[j].set(
            jnp.asarray(v, dtype=self._value.dtype)
            if not isinstance(v, (int, float, bool)) else v)

    T.__getitem__ = _getitem
    T.__setitem__ = _setitem

    def _iter(self):
        for i in range(len(self)):
            yield self[i]
    T.__iter__ = _iter

    # method versions of functional ops (paddle tensor methods)
    for mod in (math, reduction, manipulation, linalg, activation):
        for name in dir(mod):
            fn = getattr(mod, name)
            if callable(fn) and (hasattr(fn, "op_name") or
                                 name in ("concat", "split", "topk", "einsum",
                                          "multiplex", "chunk", "unbind",
                                          "expand_as", "broadcast_to", "qr",
                                          "svd", "eigh", "quantile")):
                clean = name.rstrip("_") if name in ("pow_", "slice_") else name
                _attach(clean, fn)

    _attach("mean", reduction.mean)
    _attach("sum", reduction.sum)
    _attach("max", reduction.max)
    _attach("min", reduction.min)
    _attach("prod", reduction.prod)
    _attach("all", reduction.all)
    _attach("any", reduction.any)
    _attach("abs", math.abs)
    _attach("pow", math.pow_)
    _attach("reshape", manipulation.reshape)
    _attach("flatten", manipulation.flatten)
    _attach("transpose", manipulation.transpose)
    _attach("squeeze", manipulation.squeeze)
    _attach("unsqueeze", manipulation.unsqueeze)
    _attach("matmul", linalg.matmul)
    _attach("dot", linalg.dot)
    _attach("norm", linalg.norm)
    _attach("dim", lambda s: s.ndim)

    @property
    def T_(self):
        return manipulation.transpose(self, list(range(self.ndim))[::-1])
    Tensor.T = T_
