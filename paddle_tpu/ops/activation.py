"""Activation ops.

~ python/paddle/nn/functional/activation.py over phi activation kernels
(paddle/phi/kernels/activation_kernel.h). Pure elementwise: XLA fuses these
into neighbors, so each is a one-liner on the VPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .dispatch import def_op


@def_op("relu")
def relu(x):
    return jax.nn.relu(x)


@def_op("relu6")
def relu6(x):
    return jax.nn.relu6(x)


@def_op("leaky_relu")
def leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(x, negative_slope)


@def_op("prelu")
def prelu(x, weight):
    return jnp.where(x >= 0, x, weight * x)


@def_op("elu")
def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha)


@def_op("selu")
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@def_op("celu")
def celu(x, alpha=1.0):
    return jax.nn.celu(x, alpha)


@def_op("gelu")
def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


@def_op("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@def_op("hardsigmoid")
def hardsigmoid(x, slope=1.0 / 6, offset=0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


@def_op("hardswish")
def hardswish(x):
    return x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


@def_op("hardtanh")
def hardtanh(x, min=-1.0, max=1.0):
    return jnp.clip(x, min, max)


@def_op("hardshrink")
def hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@def_op("softshrink")
def softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


@def_op("tanhshrink")
def tanhshrink(x):
    return x - jnp.tanh(x)


@def_op("silu")
def silu(x):
    return jax.nn.silu(x)


swish = silu


@def_op("mish")
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@def_op("softplus")
def softplus(x, beta=1.0, threshold=20.0):
    scaled = beta * x
    return jnp.where(scaled > threshold, x, jax.nn.softplus(scaled) / beta)


@def_op("softsign")
def softsign(x):
    return jax.nn.soft_sign(x)


@def_op("thresholded_relu")
def thresholded_relu(x, threshold=1.0):
    return jnp.where(x > threshold, x, 0.0)


@def_op("log_sigmoid")
def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


@def_op("softmax")
def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=int(axis))


@def_op("log_softmax")
def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=int(axis))


@def_op("gumbel_softmax")
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, key=None):
    g = jax.random.gumbel(key, x.shape, x.dtype) if key is not None else 0.0
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        y_hard = jnp.zeros_like(y).at[
            tuple(jnp.indices(y.shape)[i] if i != (axis % y.ndim) else idx
                  for i in range(y.ndim))].set(1.0)
        # straight-through
        y = y_hard - jax.lax.stop_gradient(y) + y
    return y


@def_op("maxout")
def maxout(x, groups, axis=1):
    c = x.shape[axis]
    new_shape = list(x.shape)
    new_shape[axis] = c // groups
    new_shape.insert(axis + 1, groups)
    return jnp.max(jnp.reshape(x, new_shape), axis=axis + 1)


@def_op("glu")
def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)
