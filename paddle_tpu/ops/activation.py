"""Activation ops.

~ python/paddle/nn/functional/activation.py over phi activation kernels
(paddle/phi/kernels/activation_kernel.h). Pure elementwise: XLA fuses these
into neighbors, so each is a one-liner on the VPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .dispatch import def_op


# The simple elementwise activations are YAML-spec-generated (specs.yaml
# group "activation"), mirroring api.yaml-driven generation; complex ops
# (randomness, shape logic) stay hand-written below.
from .codegen import generate as _generate

_GENERATED_ACTIVATIONS = _generate(globals(), groups={"activation"})

swish = silu  # noqa: F821 — generated above


@def_op("gumbel_softmax")
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, key=None):
    g = jax.random.gumbel(key, x.shape, x.dtype) if key is not None else 0.0
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        y_hard = jnp.zeros_like(y).at[
            tuple(jnp.indices(y.shape)[i] if i != (axis % y.ndim) else idx
                  for i in range(y.ndim))].set(1.0)
        # straight-through
        y = y_hard - jax.lax.stop_gradient(y) + y
    return y


@def_op("maxout")
def maxout(x, groups, axis=1):
    c = x.shape[axis]
    new_shape = list(x.shape)
    new_shape[axis] = c // groups
    new_shape.insert(axis + 1, groups)
    return jnp.max(jnp.reshape(x, new_shape), axis=axis + 1)


@def_op("glu")
def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)
