"""Reduction ops.

~ python/paddle/tensor/math.py + stat.py reductions, lowered through the phi
reduce kernel family (paddle/phi/kernels/reduce_*_kernel.h, funcs/reduce).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .dispatch import def_op, apply_op


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduce(name, jfn, nondiff=False):
    @def_op(name, nondiff=nondiff)
    def op(x, axis=None, keepdim=False):
        return jfn(x, axis=_norm_axis(axis), keepdims=keepdim)
    return op


sum = _reduce("sum", jnp.sum)  # noqa: A001
mean = _reduce("mean", jnp.mean)
prod = _reduce("prod", jnp.prod)
max = _reduce("max", jnp.max)  # noqa: A001
min = _reduce("min", jnp.min)  # noqa: A001
amax = _reduce("amax", jnp.max)
amin = _reduce("amin", jnp.min)
any = _reduce("any", jnp.any, nondiff=True)  # noqa: A001
all = _reduce("all", jnp.all, nondiff=True)  # noqa: A001
nansum = _reduce("nansum", jnp.nansum)
nanmean = _reduce("nanmean", jnp.nanmean)
median = _reduce("median", jnp.median)
nanmedian = _reduce("nanmedian", jnp.nanmedian)


@def_op("std")
def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=_norm_axis(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@def_op("var")
def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=_norm_axis(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@def_op("logsumexp")
def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=_norm_axis(axis), keepdims=keepdim)


@def_op("argmax", nondiff=True)
def argmax(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmax(x, axis=axis, keepdims=keepdim)
    return out.astype(jnp.dtype(dtype))


@def_op("argmin", nondiff=True)
def argmin(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmin(x, axis=axis, keepdims=keepdim)
    return out.astype(jnp.dtype(dtype))


@def_op("count_nonzero", nondiff=True)
def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=_norm_axis(axis), keepdims=keepdim)


def quantile(x, q, axis=None, keepdim=False):
    return apply_op(
        "quantile",
        lambda a: jnp.quantile(a, jnp.asarray(q), axis=_norm_axis(axis),
                               keepdims=keepdim),
        x)


@def_op("kthvalue")
def kthvalue(x, k, axis=-1, keepdim=False):
    srt = jnp.sort(x, axis=axis)
    val = jnp.take(srt, k - 1, axis=axis)
    if keepdim:
        val = jnp.expand_dims(val, axis)
    return val


@def_op("mode")
def mode(x, axis=-1, keepdim=False):
    srt = jnp.sort(x, axis=axis)
    mid = srt.shape[axis] // 2
    val = jnp.take(srt, mid, axis=axis)
    if keepdim:
        val = jnp.expand_dims(val, axis)
    return val
