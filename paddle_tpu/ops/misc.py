"""Remaining top-level paddle.* namespace ops.

~ scattered reference sources: python/paddle/tensor/manipulation.py (cast,
crop, reverse, unique_consecutive, tolist), math.py (add_n, increment, logit,
dist, nanquantile, tensordot, broadcast_shape), attribute.py (shape, rank,
is_complex/is_floating_point/is_integer), creation.py (complex,
create_parameter), random.py (poisson, standard_normal, randint_like), and
logic.py (is_empty, is_tensor). These round out the public `paddle.`
namespace to parity; all lower to single jnp calls XLA fuses freely.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor
from ..core import dtype as dtypes
from ..core.generator import default_generator
from .dispatch import def_op


@def_op("cast")
def cast(x, dtype):
    return x.astype(dtypes.convert_dtype(dtype))


def add_n(inputs):
    """~ paddle.add_n — sum of a tensor list; tape-recorded via `+`."""
    if isinstance(inputs, (list, tuple)):
        out = inputs[0]
        for t in inputs[1:]:
            out = out + t
        return out
    return inputs if isinstance(inputs, Tensor) else Tensor(inputs)


@def_op("logit")
def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


@def_op("dist")
def dist(x, y, p=2):
    d = jnp.abs(x - y)
    p = float(p)
    if p == float("inf"):
        return jnp.max(d)
    if p == float("-inf"):
        return jnp.min(d)
    if p == 0:
        return jnp.sum((d != 0).astype(x.dtype))
    return jnp.sum(d ** p) ** (1.0 / p)


@def_op("nanquantile")
def nanquantile(x, q, axis=None, keepdim=False):
    return jnp.nanquantile(x, q, axis=axis, keepdims=keepdim)


@def_op("tensordot")
def tensordot(x, y, axes=2):
    if isinstance(axes, Tensor):
        axes = axes.tolist()
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(a.tolist() if isinstance(a, Tensor) else a)
                     if isinstance(a, (list, tuple, Tensor)) else a
                     for a in axes)
    return jnp.tensordot(x, y, axes=axes)


@def_op("crop")
def crop(x, shape=None, offsets=None):
    ndim = x.ndim
    if shape is None:
        shape = list(x.shape)
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    shape = [int(s._value) if isinstance(s, Tensor) else int(s) for s in shape]
    shape = [x.shape[i] if shape[i] in (-1, 0) else shape[i]
             for i in range(ndim)]
    if offsets is None:
        offsets = [0] * ndim
    if isinstance(offsets, Tensor):
        offsets = offsets.tolist()
    offsets = [int(o._value) if isinstance(o, Tensor) else int(o)
               for o in offsets]
    idx = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return x[idx]


@def_op("reverse")
def reverse(x, axis):
    if isinstance(axis, int):
        axis = [axis]
    return jnp.flip(x, axis=tuple(axis))


@def_op("complex")
def complex(real, imag):  # noqa: A001 - mirrors paddle.complex
    return jax.lax.complex(real, imag)


@def_op("floor_mod")
def floor_mod(x, y):
    return jnp.mod(x, y)


# ---- predicates / attributes (non-traced, host-side) -----------------------

def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


def is_complex(x) -> bool:
    return bool(jnp.issubdtype(_dt(x), jnp.complexfloating))


def is_floating_point(x) -> bool:
    return bool(jnp.issubdtype(_dt(x), jnp.floating))


def is_integer(x) -> bool:
    return bool(jnp.issubdtype(_dt(x), jnp.integer))


def _dt(x):
    return x._value.dtype if isinstance(x, Tensor) else jnp.asarray(x).dtype


def shape(x):
    """~ paddle.shape: runtime shape as an int32 tensor."""
    return Tensor(jnp.asarray(x._value.shape if isinstance(x, Tensor)
                              else np.shape(x), dtype=jnp.int32))


def rank(x):
    return Tensor(jnp.asarray(x.ndim if isinstance(x, Tensor)
                              else np.ndim(x), dtype=jnp.int32))


def numel(x):
    n = int(np.prod(x.shape)) if x.shape else 1
    return Tensor(jnp.asarray(n, dtype=jnp.int64
                              if jax.config.jax_enable_x64 else jnp.int32))


def is_empty(x):
    return Tensor(jnp.asarray(x.size == 0))


def tolist(x):
    return x.tolist() if isinstance(x, Tensor) else np.asarray(x).tolist()


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def increment(x, value=1.0):
    """~ paddle.increment — in-place add on a 1-element tensor."""
    x._value = x._value + jnp.asarray(value, dtype=x._value.dtype)
    return x


# ---- random ---------------------------------------------------------------

def poisson(x):
    key = default_generator().next_key()
    lam = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.poisson(key, lam).astype(lam.dtype))


def standard_normal(shape, dtype=None, name=None):
    from .creation import randn
    return randn(shape, dtype=dtype)


def randint_like(x, low=0, high=None, dtype=None):
    from .creation import randint
    target = dtypes.convert_dtype(dtype if dtype is not None else x.dtype)
    if jnp.issubdtype(target, jnp.integer):
        return randint(low, high, shape=x.shape, dtype=target)
    out = randint(low, high, shape=x.shape, dtype="int32")
    return Tensor(out._value.astype(target))


# ---- misc host-side utilities --------------------------------------------

def create_parameter(shape, dtype, name=None, attr=None,
                     default_initializer=None):
    """~ paddle.create_parameter (python/paddle/tensor/creation.py)."""
    shape = [int(s) for s in shape]
    jdt = dtypes.convert_dtype(dtype)
    if default_initializer is not None:
        p = Parameter(jnp.asarray(default_initializer(shape, jdt)))
        if name:
            p.name = name
        return p
    if jnp.issubdtype(jdt, jnp.floating):
        fan_in = shape[0] if shape else 1
        limit = float(np.sqrt(6.0 / max(1, fan_in)))
        val = jax.random.uniform(default_generator().next_key(), shape,
                                 jdt, -limit, limit)
    else:
        val = jnp.zeros(shape, jdt)
    p = Parameter(val)
    if name:
        p.name = name
    return p


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64"):
    """~ paddle.unique_consecutive — data-dependent output size, so this is
    an eager/host op (the reference's GPU kernel is likewise sync)."""
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    if axis is None:
        flat = arr.reshape(-1)
        if flat.size == 0:
            outs = [Tensor(jnp.asarray(flat))]
            if return_inverse:
                outs.append(Tensor(jnp.asarray(np.zeros(0, np.int32))))
            if return_counts:
                outs.append(Tensor(jnp.asarray(np.zeros(0, np.int32))))
            return outs[0] if len(outs) == 1 else tuple(outs)
        change = np.concatenate([[True], flat[1:] != flat[:-1]])
        out = flat[change]
        inv = np.cumsum(change) - 1
        counts = np.diff(np.concatenate(
            [np.nonzero(change)[0], [flat.size]]))
    else:
        moved = np.moveaxis(arr, axis, 0)
        if moved.shape[0] == 0:
            change = np.zeros(0, bool)
        else:
            flat2 = moved.reshape(moved.shape[0], -1)
            change = np.concatenate(
                [[True], np.any(flat2[1:] != flat2[:-1], axis=1)])
        out = np.moveaxis(moved[change], 0, axis)
        inv = np.cumsum(change) - 1
        counts = np.diff(np.concatenate(
            [np.nonzero(change)[0], [moved.shape[0]]]))
    outs = [Tensor(jnp.asarray(out))]
    if return_inverse:
        outs.append(Tensor(jnp.asarray(inv.astype(np.int32))))
    if return_counts:
        outs.append(Tensor(jnp.asarray(counts.astype(np.int32))))
    return outs[0] if len(outs) == 1 else tuple(outs)


# ---- in-place variants ----------------------------------------------------

def _inplace(fn):
    def wrapper(x, *args, **kwargs):
        out = fn(x, *args, **kwargs)
        x._value = out._value
        return x
    wrapper.__name__ = fn.__name__ + "_"
    return wrapper


def _install_inplace():
    from . import manipulation, activation, math
    mapping = {}
    mapping["reshape_"] = _inplace(manipulation.reshape)
    mapping["squeeze_"] = _inplace(manipulation.squeeze)
    mapping["unsqueeze_"] = _inplace(manipulation.unsqueeze)
    mapping["flatten_"] = _inplace(manipulation.flatten)
    mapping["scatter_"] = _inplace(manipulation.scatter)
    mapping["tanh_"] = _inplace(math.tanh)
    mapping["exp_"] = _inplace(math.exp)
    mapping["sqrt_"] = _inplace(math.sqrt)
    mapping["rsqrt_"] = _inplace(math.rsqrt)
    mapping["clip_"] = _inplace(math.clip)
    mapping["ceil_"] = _inplace(math.ceil)
    mapping["floor_"] = _inplace(math.floor)
    mapping["round_"] = _inplace(math.round)
    mapping["reciprocal_"] = _inplace(math.reciprocal)
    mapping["subtract_"] = _inplace(math.subtract)
    mapping["add_"] = _inplace(math.add)
    mapping["scale_"] = _inplace(math.scale)
    mapping["zero_"] = _inplace(lambda x: Tensor(jnp.zeros_like(x._value)))
    mapping["fill_"] = _inplace(
        lambda x, v: Tensor(jnp.full_like(x._value, v)))
    for name, fn in mapping.items():
        globals()[name] = fn
        setattr(Tensor, name, fn)
    return list(mapping)


_INPLACE_NAMES = _install_inplace()

__all__ = [
    "cast", "add_n", "logit", "dist", "nanquantile", "tensordot", "crop",
    "reverse", "complex", "floor_mod", "is_tensor", "is_complex",
    "is_floating_point", "is_integer", "shape", "rank", "numel", "is_empty",
    "tolist", "broadcast_shape", "increment", "poisson", "standard_normal",
    "randint_like", "create_parameter", "unique_consecutive",
] + _INPLACE_NAMES
