"""Tensor creation ops.

~ python/paddle/tensor/creation.py backed by phi full/empty/arange kernels
(paddle/phi/kernels/full_kernel.h etc.).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dt
from ..core import generator as _gen
from ..core.tensor import Tensor, to_tensor
from .dispatch import def_op, apply_op


def _dtype_or_default(dtype):
    return _dt.convert_dtype(dtype) if dtype is not None else _dt.get_default_dtype()


def _shape_list(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        shape = [int(shape)]
    return [int(s._value) if isinstance(s, Tensor) else int(s) for s in shape]


def full(shape, fill_value, dtype=None):
    return Tensor(jnp.full(_shape_list(shape), fill_value, _dtype_or_default(dtype)))


def zeros(shape, dtype=None):
    return Tensor(jnp.zeros(_shape_list(shape), _dtype_or_default(dtype)))


def ones(shape, dtype=None):
    return Tensor(jnp.ones(_shape_list(shape), _dtype_or_default(dtype)))


@def_op("full_like")
def _full_like(x, *, fill_value, dtype=None):
    return jnp.full_like(x, fill_value, dtype=dtype)


def full_like(x, fill_value, dtype=None):
    return _full_like(x, fill_value=fill_value,
                      dtype=_dt.convert_dtype(dtype) if dtype else None)


def zeros_like(x, dtype=None):
    return full_like(x, 0, dtype)


def ones_like(x, dtype=None):
    return full_like(x, 1, dtype)


def empty(shape, dtype=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    start = start.item() if isinstance(start, Tensor) else start
    end = end.item() if isinstance(end, Tensor) else end
    step = step.item() if isinstance(step, Tensor) else step
    if dtype is None:
        dtype = np.int64 if all(
            isinstance(v, (int, np.integer)) for v in (start, end, step)) \
            else _dt.get_default_dtype()
    return Tensor(jnp.arange(start, end, step, dtype=_dt.convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None):
    return Tensor(jnp.linspace(float(start), float(stop), int(num),
                               dtype=_dtype_or_default(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None):
    return Tensor(jnp.logspace(float(start), float(stop), int(num), base=base,
                               dtype=_dtype_or_default(dtype)))


def eye(num_rows, num_columns=None, dtype=None):
    return Tensor(jnp.eye(int(num_rows),
                          int(num_columns) if num_columns else None,
                          dtype=_dtype_or_default(dtype)))


@def_op("tril")
def tril(x, diagonal=0):
    return jnp.tril(x, k=int(diagonal))


@def_op("triu")
def triu(x, diagonal=0):
    return jnp.triu(x, k=int(diagonal))


@def_op("diag")
def diag(x, offset=0):
    return jnp.diag(x, k=int(offset))


@def_op("diagflat")
def diagflat(x, offset=0):
    return jnp.diagflat(x, k=int(offset))


def meshgrid(*args):
    arrs = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
            for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    return [Tensor(m) for m in jnp.meshgrid(*arrs, indexing="ij")]


def assign(x, output=None):
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    if output is not None:
        output.set_value(v)
        return output
    return apply_op("assign", lambda a: a + 0, x if isinstance(x, Tensor) else Tensor(v))


def clone(x):
    return apply_op("clone", lambda a: a + 0, x)


# ---- random creation ops (consume the global Generator: seed+offset) -------

def rand(shape, dtype=None):
    return Tensor(jax.random.uniform(_gen.next_key(), _shape_list(shape),
                                     dtype=_dtype_or_default(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0):
    key = jax.random.PRNGKey(seed) if seed else _gen.next_key()
    return Tensor(jax.random.uniform(key, _shape_list(shape),
                                     dtype=_dtype_or_default(dtype),
                                     minval=min, maxval=max))


def randn(shape, dtype=None):
    return Tensor(jax.random.normal(_gen.next_key(), _shape_list(shape),
                                    dtype=_dtype_or_default(dtype)))


def normal(mean=0.0, std=1.0, shape=None):
    if shape is None:
        shape = []
    z = jax.random.normal(_gen.next_key(), _shape_list(shape),
                          dtype=_dt.get_default_dtype())
    return Tensor(z * std + mean)


def randint(low=0, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(_gen.next_key(), _shape_list(shape),
                                     int(low), int(high),
                                     dtype=_dt.convert_dtype(dtype)))


def randperm(n, dtype="int64"):
    return Tensor(jax.random.permutation(_gen.next_key(), int(n))
                  .astype(_dt.convert_dtype(dtype)))


def bernoulli(x):
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.bernoulli(_gen.next_key(), v).astype(v.dtype))


def multinomial(x, num_samples=1, replacement=False):
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    logits = jnp.log(jnp.maximum(v, 1e-30))
    if replacement:
        out = jax.random.categorical(_gen.next_key(), logits, axis=-1,
                                     shape=(*v.shape[:-1], int(num_samples)))
    else:
        key = _gen.next_key()
        g = jax.random.gumbel(key, v.shape)
        out = jnp.argsort(-(logits + g), axis=-1)[..., :int(num_samples)]
    return Tensor(out.astype(jnp.int64))
