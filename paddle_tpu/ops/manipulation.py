"""Shape / layout manipulation ops.

~ python/paddle/tensor/manipulation.py over phi reshape/transpose/concat/
split/gather/scatter kernels. All are pure-metadata or gather/scatter ops
that XLA lowers to copies or fused reindexing.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .dispatch import def_op, apply_op


def _shape_list(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    return [int(s._value) if isinstance(s, Tensor) else int(s) for s in shape]


@def_op("reshape")
def reshape(x, shape):
    return jnp.reshape(x, _shape_list(shape))


@def_op("flatten")
def flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if nd == 0:
        return jnp.reshape(x, (1,))
    start = start_axis % nd
    stop = stop_axis % nd
    new_shape = (x.shape[:start]
                 + (int(np.prod(x.shape[start:stop + 1])),)
                 + x.shape[stop + 1:])
    return jnp.reshape(x, new_shape)


@def_op("transpose")
def transpose(x, perm=None):
    return jnp.transpose(x, axes=perm)


@def_op("moveaxis")
def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


@def_op("swapaxes")
def swapaxes(x, axis1, axis2):
    return jnp.swapaxes(x, int(axis1), int(axis2))


@def_op("squeeze")
def squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, (list, tuple)):
        axis = tuple(a for a in axis if x.shape[a] == 1)
        return jnp.squeeze(x, axis=axis) if axis else x
    return jnp.squeeze(x, axis=axis) if x.shape[axis] == 1 else x


@def_op("unsqueeze")
def unsqueeze(x, axis):
    if isinstance(axis, (list, tuple)):
        for a in sorted(axis):
            x = jnp.expand_dims(x, a)
        return x
    return jnp.expand_dims(x, int(axis))


def concat(x, axis=0):
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply_op("concat", lambda *vs: jnp.concatenate(vs, axis=axis), *x)


def stack(x, axis=0):
    return apply_op("stack", lambda *vs: jnp.stack(vs, axis=int(axis)), *x)


def split(x, num_or_sections, axis=0):
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    dim = x.shape[axis] if isinstance(x, Tensor) else x.shape[axis]
    if isinstance(num_or_sections, int):
        sections = [dim // num_or_sections] * num_or_sections
    else:
        sections = [int(s) for s in num_or_sections]
        # -1 wildcard: fill remaining
        if -1 in sections:
            known = sum(s for s in sections if s != -1)
            sections[sections.index(-1)] = dim - known
    offsets = np.cumsum(sections)[:-1].tolist()
    out = apply_op("split",
                   lambda v: tuple(jnp.split(v, offsets, axis=axis)), x)
    return list(out) if isinstance(out, tuple) else [out]


def chunk(x, chunks, axis=0):
    return split(x, int(chunks), axis)


def unbind(x, axis=0):
    n = x.shape[axis]
    outs = split(x, n, axis)
    return [squeeze(o, axis=axis) for o in outs]


@def_op("tile")
def tile(x, repeat_times):
    return jnp.tile(x, tuple(int(r) for r in repeat_times))


@def_op("repeat_interleave")
def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@def_op("expand")
def expand(x, shape):
    shape = _shape_list(shape)
    # paddle semantics: -1 keeps original dim
    xshape = list(x.shape)
    pad = len(shape) - len(xshape)
    full = []
    for i, s in enumerate(shape):
        if s == -1:
            full.append(xshape[i - pad])
        else:
            full.append(s)
    return jnp.broadcast_to(x, tuple(full))


def expand_as(x, y):
    return expand(x, list(y.shape))


def broadcast_to(x, shape):
    return expand(x, shape)


def broadcast_tensors(inputs):
    shapes = [tuple(t.shape) for t in inputs]
    out_shape = np.broadcast_shapes(*shapes)
    return [expand(t, list(out_shape)) for t in inputs]


@def_op("flip")
def flip(x, axis):
    if isinstance(axis, int):
        axis = [axis]
    return jnp.flip(x, axis=tuple(axis))


@def_op("roll")
def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


@def_op("rot90")
def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=tuple(axes))


@def_op("slice")
def slice_(x, axes, starts, ends):
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = slice(int(st), int(en))
    return x[tuple(idx)]


@def_op("strided_slice")
def strided_slice(x, axes, starts, ends, strides):
    idx = [slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = slice(int(st), int(en), int(sd))
    return x[tuple(idx)]


@def_op("gather")
def gather(x, index, axis=0):
    index = index.reshape(-1)
    return jnp.take(x, index, axis=int(axis))


@def_op("gather_nd")
def gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@def_op("take_along_axis")
def take_along_axis(x, indices, axis):
    return jnp.take_along_axis(x, indices, axis=int(axis))


@def_op("put_along_axis")
def put_along_axis(x, indices, values, axis, reduce="assign"):
    if reduce == "assign":
        return jnp.put_along_axis(x, indices, values, axis=int(axis),
                                  inplace=False)
    dims = [0] * x.ndim  # scatter via .at
    del dims
    if reduce == "add":
        idx = _along_axis_index(x, indices, int(axis))
        return x.at[idx].add(values)
    if reduce == "multiply":
        idx = _along_axis_index(x, indices, int(axis))
        return x.at[idx].multiply(values)
    raise ValueError(f"unknown reduce {reduce}")


def _along_axis_index(x, indices, axis):
    ix = []
    for d in range(x.ndim):
        if d == axis:
            ix.append(indices)
        else:
            shp = [1] * x.ndim
            shp[d] = x.shape[d]
            ix.append(jnp.arange(x.shape[d]).reshape(shp))
    return tuple(ix)


@def_op("scatter")
def scatter(x, index, updates, overwrite=True):
    index = index.reshape(-1)
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


@def_op("scatter_nd_add")
def scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd(index, updates, shape):
    def _snd(index, updates):
        zeros = jnp.zeros(_shape_list(shape), updates.dtype)
        idx = tuple(jnp.moveaxis(index, -1, 0))
        return zeros.at[idx].add(updates)
    return apply_op("scatter_nd", _snd, index, updates)


@def_op("index_select")
def index_select(x, index, axis=0):
    return jnp.take(x, index.reshape(-1), axis=int(axis))


@def_op("index_sample")
def index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


@def_op("masked_select")
def masked_select(x, mask):
    # dynamic shape: falls back to host-side compress (not jittable);
    # mirrored from phi masked_select which is also dynamic-output.
    return x[mask]


@def_op("masked_fill")
def masked_fill(x, mask, value):
    return jnp.where(mask, value, x)


@def_op("index_put")
def index_put(x, indices, value, accumulate=False):
    # indices arrive as a python tuple, outside the dispatch unwrap:
    # Tensor entries must be unwrapped by hand or jnp indexing rejects them
    idx = tuple(i._value if isinstance(i, Tensor) else i for i in indices)
    if accumulate:
        return x.at[idx].add(value)
    return x.at[idx].set(value)


@def_op("pad")
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    # paddle pad: list [before_last... ] or full pairs
    if len(pad) == 2 * x.ndim:
        pairs = [(int(pad[2 * i]), int(pad[2 * i + 1])) for i in range(x.ndim)]
    else:
        # pad applies to trailing spatial dims (NCHW/NCL/NCDHW conventions)
        n_spatial = len(pad) // 2
        pairs = [(0, 0)] * (x.ndim - n_spatial)
        if data_format.endswith("C"):  # NHWC-style: spatial dims before channel
            pairs = [(0, 0)]
            for i in range(n_spatial):
                pairs.append((int(pad[2 * i]), int(pad[2 * i + 1])))
            pairs.append((0, 0))
            pairs = pairs[:x.ndim]
        else:
            spat = [(int(pad[2 * i]), int(pad[2 * i + 1])) for i in range(n_spatial)]
            pairs = [(0, 0)] * (x.ndim - n_spatial) + spat
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, pairs, mode="constant", constant_values=value)
    return jnp.pad(x, pairs, mode=jmode)


@def_op("unstack")
def _noop(x):  # placeholder to keep name free
    return x


def unstack(x, axis=0, num=None):
    return unbind(x, axis)


@def_op("unique", nondiff=True)
def unique(x, return_index=False, return_inverse=False, return_counts=False):
    res = jnp.unique(x, return_index=return_index,
                     return_inverse=return_inverse, return_counts=return_counts)
    return res


@def_op("nonzero", nondiff=True)
def nonzero(x, as_tuple=False):
    nz = jnp.nonzero(x)
    if as_tuple:
        return nz
    return jnp.stack(nz, axis=1)


@def_op("sort")
def sort(x, axis=-1, descending=False):
    out = jnp.sort(x, axis=axis)
    if descending:
        out = jnp.flip(out, axis=axis)
    return out


@def_op("argsort", nondiff=True)
def argsort(x, axis=-1, descending=False):
    out = jnp.argsort(x, axis=axis)
    if descending:
        out = jnp.flip(out, axis=axis)
    return out.astype(jnp.int64)


def topk(x, k, axis=-1, largest=True, sorted=True):
    def _topk(v):
        if axis not in (-1, v.ndim - 1):
            v2 = jnp.moveaxis(v, axis, -1)
        else:
            v2 = v
        if largest:
            vals, idx = jax.lax.top_k(v2, int(k))
        else:
            vals, idx = jax.lax.top_k(-v2, int(k))
            vals = -vals
        if axis not in (-1, v.ndim - 1):
            vals = jnp.moveaxis(vals, -1, axis)
            idx = jnp.moveaxis(idx, -1, axis)
        return vals, idx.astype(jnp.int64)
    return apply_op("topk", _topk, x)


@def_op("searchsorted", nondiff=True)
def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    out = jnp.searchsorted(sorted_sequence, values, side=side)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@def_op("bincount", nondiff=True)
def bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=int(minlength))


@def_op("one_hot", nondiff=True)
def one_hot(x, num_classes):
    return jax.nn.one_hot(x, int(num_classes), dtype=jnp.float32)


@def_op("as_real", nondiff=True)
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@def_op("as_complex")
def as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def _shard(x):
        size = index_num // nshards
        lo, hi = shard_id * size, (shard_id + 1) * size
        ok = (x >= lo) & (x < hi)
        return jnp.where(ok, x - lo, ignore_value)
    return apply_op("shard_index", _shard, input, nondiff=True)
