"""Elementwise + binary math ops.

~ python/paddle/tensor/math.py lowered through phi elementwise kernels
(paddle/phi/kernels/elementwise_*_kernel.h, funcs/broadcast_function.h).
Broadcasting is jnp's; XLA fuses chains of these into single kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .dispatch import def_op, apply_op


# The elementwise unary/binary families are YAML-spec-generated
# (ops/specs.yaml group "math" -> ops/codegen.py), mirroring the
# reference's api.yaml-driven API generation; each generated op carries
# eager dispatch, derived VJP, static capture and eval_shape infermeta.
from .codegen import generate as _generate

_GENERATED_MATH = _generate(globals(), groups={"math"})

pow_ = globals()["pow"]  # historical alias (tensor_methods __pow__)
remainder = mod  # noqa: F821 — generated above


@def_op("clip")
def clip(x, min=None, max=None):
    return jnp.clip(x, min, max)


@def_op("lerp")
def lerp(x, y, weight):
    return x + weight * (y - x)


@def_op("stanh")
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


@def_op("scale")
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


def multiplex(inputs, index):
    def _mx(index, *ins):
        stacked = jnp.stack(ins, axis=0)
        idx = index.reshape(-1).astype(jnp.int32)
        return stacked[idx, jnp.arange(stacked.shape[1])]
    return apply_op("multiplex", _mx, index, *inputs)


@def_op("addmm")
def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * (x @ y)


@def_op("inner")
def inner(x, y):
    return jnp.inner(x, y)


@def_op("outer")
def outer(x, y):
    return jnp.outer(x, y)


@def_op("kron")
def kron(x, y):
    return jnp.kron(x, y)


@def_op("trace")
def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@def_op("diagonal")
def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


@def_op("cumsum")
def cumsum(x, axis=None):
    return jnp.cumsum(x, axis=axis)


@def_op("cumprod")
def cumprod(x, dim=None):
    return jnp.cumprod(x, axis=dim)


@def_op("logcumsumexp")
def logcumsumexp(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jax.lax.cumlogsumexp(x, axis=axis)


def isfinite(x):
    return apply_op("isfinite", jnp.isfinite, x, nondiff=True)


def isinf(x):
    return apply_op("isinf", jnp.isinf, x, nondiff=True)


def isnan(x):
    return apply_op("isnan", jnp.isnan, x, nondiff=True)


# ---- logical / bitwise -----------------------------------------------------

def _nondiff_binop(name, jfn):
    @def_op(name, nondiff=True)
    def op(x, y):
        return jfn(x, y)
    return op


logical_and = _nondiff_binop("logical_and", jnp.logical_and)
logical_or = _nondiff_binop("logical_or", jnp.logical_or)
logical_xor = _nondiff_binop("logical_xor", jnp.logical_xor)
bitwise_and = _nondiff_binop("bitwise_and", jnp.bitwise_and)
bitwise_or = _nondiff_binop("bitwise_or", jnp.bitwise_or)
bitwise_xor = _nondiff_binop("bitwise_xor", jnp.bitwise_xor)
left_shift = _nondiff_binop("left_shift", jnp.left_shift)
right_shift = _nondiff_binop("right_shift", jnp.right_shift)


@def_op("logical_not", nondiff=True)
def logical_not(x):
    return jnp.logical_not(x)


@def_op("bitwise_not", nondiff=True)
def bitwise_not(x):
    return jnp.bitwise_not(x)


# ---- comparison ------------------------------------------------------------

equal = _nondiff_binop("equal", lambda x, y: jnp.equal(x, y))
not_equal = _nondiff_binop("not_equal", jnp.not_equal)
greater_than = _nondiff_binop("greater_than", jnp.greater)
greater_equal = _nondiff_binop("greater_equal", jnp.greater_equal)
less_than = _nondiff_binop("less_than", jnp.less)
less_equal = _nondiff_binop("less_equal", jnp.less_equal)


def equal_all(x, y):
    return apply_op("equal_all", lambda a, b: jnp.array_equal(a, b), x, y,
                    nondiff=True)


@def_op("allclose", nondiff=True)
def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@def_op("isclose", nondiff=True)
def isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@def_op("where")
def where(condition, x, y):
    return jnp.where(condition, x, y)


@def_op("nan_to_num")
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)
