"""Chunked-vocabulary causal-LM cross entropy: loss without the logits.

~ the reference's c_softmax_with_cross_entropy op family
(operators/collective/c_softmax_with_cross_entropy_op.cu) solves vocab
pressure by SHARDING logits over tensor parallelism; this solves the
orthogonal single-chip problem: at Llama-3 scale (V=128256) the (B*S, V)
bf16 logits tensor is ~4.2 GB at B=8/S=2048 — materializing it costs
HBM capacity plus three full HBM round-trips (head-matmul write, CE
read, backward read). Here the head projection and the CE fuse: a
lax.scan walks vocab chunks, each chunk's logits live only as a
(B*S, chunk) VMEM/HBM temporary inside one scan step, and the backward
recomputes each chunk's softmax from the saved online logsumexp
(flash-attention's trick applied to the vocab axis).

Memory: O(B*S*chunk) working set vs O(B*S*V); FLOPs: the same head
matmul + one recompute of it in the backward (2x head FLOPs for
V-independent memory — the classic rematerialization trade).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG = -1e30


def _num_chunks(V, chunk):
    # ceil: a partial last chunk is handled by padding w with zero rows
    # and NEG-masking the out-of-vocab columns (real vocabs like
    # Llama-3's 128256 rarely have convenient divisors)
    return -(-V // chunk)


def _padded(w, C, chunk):
    V = w.shape[0]
    pad = C * chunk - V
    return w if pad == 0 else jnp.pad(w, ((0, pad), (0, 0)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def chunked_causal_lm_loss(x, w, labels, chunk_size=16384):
    """Mean CE of softmax(x @ w.T) against labels, without materializing
    the full logits.

    x: (B, S, H) activations (bf16/f32); w: (V, H) head weights (the
    tied-embedding layout Llama uses — vocab-major chunks cleanly);
    labels: (B, S) int32, position-aligned (callers shift, the family
    convention). Returns the scalar mean loss in f32.
    """
    loss, _ = _fwd_impl(x, w, labels, chunk_size)
    return loss


def _fwd_impl(x, w, labels, chunk):
    B, S, H = x.shape
    V = w.shape[0]
    C = _num_chunks(V, chunk)
    N = B * S
    x2 = x.reshape(N, H)
    lbl = labels.reshape(N)

    wp = _padded(w, C, chunk)

    def body(carry, ci):
        m, l, lab = carry
        wc = jax.lax.dynamic_slice_in_dim(wp, ci * chunk, chunk, 0)
        lg = jax.lax.dot_general(
            x2, wc, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (N, chunk)
        col = ci * chunk + jax.lax.broadcasted_iota(
            jnp.int32, (1, chunk), 1)
        lg = jnp.where(col < V, lg, NEG)  # out-of-vocab pad columns
        m_new = jnp.maximum(m, jnp.max(lg, axis=1))
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(lg - m_new[:, None]), axis=1)
        off = lbl - ci * chunk
        in_c = (off >= 0) & (off < chunk)
        picked = jnp.take_along_axis(
            lg, jnp.clip(off, 0, chunk - 1)[:, None], 1)[:, 0]
        lab = jnp.where(in_c, picked, lab)
        return (m_new, l, lab), None

    init = (jnp.full((N,), NEG, jnp.float32),
            jnp.zeros((N,), jnp.float32),
            jnp.full((N,), NEG, jnp.float32))
    (m, l, lab), _ = jax.lax.scan(body, init, jnp.arange(C))
    lse = m + jnp.log(l)
    loss = jnp.mean(lse - lab)
    return loss, (x2, w, lbl, lse, (B, S, H))


def _fwd_vjp(x, w, labels, chunk):
    # custom_vjp passes nondiff args IN POSITION to fwd (bwd gets them
    # moved to the front)
    loss, res = _fwd_impl(x, w, labels, chunk)
    return loss, res


def _bwd_vjp(chunk, res, g):
    x2, w, lbl, lse, (B, S, H) = res
    V = w.shape[0]
    C = _num_chunks(V, chunk)
    N = B * S
    scale = g / N  # d(mean)/d(per-row)

    wp = _padded(w, C, chunk)

    def body(dx, ci):
        wc = jax.lax.dynamic_slice_in_dim(wp, ci * chunk, chunk, 0)
        lg = jax.lax.dot_general(
            x2, wc, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        col = ci * chunk + jax.lax.broadcasted_iota(
            jnp.int32, (1, chunk), 1)
        lg = jnp.where(col < V, lg, NEG)
        p = jnp.exp(lg - lse[:, None])  # softmax rows for this chunk
        off = lbl - ci * chunk
        in_c = (off >= 0) & (off < chunk)
        onehot = (jax.lax.broadcasted_iota(jnp.int32, (N, chunk), 1)
                  == jnp.clip(off, 0, chunk - 1)[:, None]) \
            & in_c[:, None]
        d_lg = (p - onehot.astype(jnp.float32)) * scale  # (N, chunk)
        d_lg = d_lg.astype(x2.dtype)
        dx = dx + jax.lax.dot_general(
            d_lg, wc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dwc = jax.lax.dot_general(
            d_lg, x2, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # (chunk, H)
        # downcast inside the body: chunks never accumulate across scan
        # steps, so this is bit-identical to a post-hoc astype while the
        # stacked (C*chunk, H) ys buffer shrinks to w.dtype (f32 stacking
        # at V=128k/H=1536 would be a ~788 MB temporary — material for
        # an op whose purpose is HBM savings)
        return dx, dwc.astype(w.dtype)

    dx0 = jnp.zeros((N, H), jnp.float32)
    dx, dwcs = jax.lax.scan(body, dx0, jnp.arange(C))
    dw = dwcs.reshape(C * chunk, H)[:V]
    return (dx.reshape(B, S, H).astype(x2.dtype), dw, None)


chunked_causal_lm_loss.defvjp(_fwd_vjp, _bwd_vjp)
