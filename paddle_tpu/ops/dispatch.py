"""Op dispatch: the eager kernel-launch path.

TPU-native equivalent of the reference's generated dygraph functions +
kernel selection stack:
  _C_ops.final_state_X -> dygraph_function -> phi::KernelFactory::
  SelectKernelOrThrowError (phi/core/kernel_factory.h:271) -> kernel launch.

Here each op is a jax-traceable function; "kernel selection" is XLA's job.
What this layer adds, mirroring the generated eager forward functions
(eager/auto_code_generator/final_state_generator/eager_gen.py output):
  1. unwrap Tensor args to jax values,
  2. AMP auto-cast hook (~ eager_amp_auto_cast.h),
  3. record a GradNode via jax.vjp when grad is required (~ CreateGradNode +
     TensorWrapper saves),
  4. wrap outputs back into Tensors,
  5. optional nan/inf scan (~ FLAGS_check_nan_inf, framework/operator.cc:1270).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd import tape as _tape
from ..core import flags as _flags
from ..core.tensor import Tensor
from ..core import dtype as _dtypes

__all__ = ["apply_op", "def_op", "OP_REGISTRY"]

# name -> python api fn; the registry role of phi::KernelFactory, keyed by op
# name only (backend/layout/dtype keys collapse: XLA compiles for the device).
OP_REGISTRY: dict[str, Callable] = {}

# flipped by paddle_tpu.static.graph.enable_static(); when True, ops whose
# inputs include symbolic StaticVars are captured into the default Program
# instead of executing (~ LayerHelper.append_op vs the eager trampoline)
STATIC_MODE = False


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def _is_diff_tensor(x) -> bool:
    return (isinstance(x, Tensor) and not x.stop_gradient
            and _dtypes.is_floating_point(x._value.dtype))


def _check_nan_inf(name: str, vals) -> None:
    for v in vals:
        if isinstance(v, jax.Array) and jnp.issubdtype(v.dtype, jnp.inexact):
            if bool(jnp.any(~jnp.isfinite(v))):
                raise FloatingPointError(
                    f"nan/inf detected in output of op '{name}' "
                    "(FLAGS_check_nan_inf=1)")


# lazily-bound module refs for the per-op hot path (importing at module
# load would cycle through the package __init__; importing per call costs
# ~1.5us/op of import-machinery lookups — measured in tools/op_bench.py
# --eager-vs-jit)
_spans = None
_amp = None


def _bind_hot_modules():
    global _spans, _amp
    from .. import amp as am
    from ..profiler import _spans as sp
    _spans = sp
    _amp = am


def apply_op(name: str, fn: Callable, *args, nondiff: bool = False, **kwargs):
    """Run one op eagerly with tape recording.

    ``fn`` must be jax-traceable over its array-positional args; kwargs are
    static attributes. Tensor positional args are unwrapped; non-Tensor
    positional args pass through untouched.
    """
    if _spans is None:
        _bind_hot_modules()
    if _spans.enabled:
        import time as _time
        _t0 = _time.perf_counter()
        try:
            return _apply_op_inner(name, fn, args, kwargs, nondiff)
        finally:
            import threading as _th
            _spans.add(f"op::{name}", _t0, _time.perf_counter() - _t0,
                       _th.get_ident())
    return _apply_op_inner(name, fn, args, kwargs, nondiff)


def _apply_op_inner(name, fn, args, kwargs, nondiff):
    if STATIC_MODE and any(getattr(a, "_symbolic", False) for a in args):
        from ..static import graph as _sg
        return _sg.capture(name, fn, args, kwargs)
    vals = [_unwrap(a) for a in args]
    if getattr(_amp._state, "amp", None) is not None:
        vals = _amp._maybe_cast(name, vals)
    grad_wanted = (not nondiff) and _tape.grad_enabled() and any(
        _is_diff_tensor(a) for a in args)

    if not grad_wanted:
        out = fn(*vals, **kwargs)
        return _wrap_outputs(name, out, stop_gradient=True)

    diff_idx = [i for i, a in enumerate(args) if _is_diff_tensor(a)]

    def closed(*dvals):
        merged = list(vals)
        for i, v in zip(diff_idx, dvals):
            merged[i] = v
        return fn(*merged, **kwargs)

    out, vjp_fn = jax.vjp(closed, *[vals[i] for i in diff_idx])
    outs, single = (out, False) if isinstance(out, (tuple, list)) else ((out,), True)

    node = _tape.GradNode(
        name, vjp_fn,
        inputs=[args[i] for i in diff_idx],
        out_avals=[(tuple(o.shape), o.dtype) for o in outs])

    tensors = []
    for i, o in enumerate(outs):
        t = Tensor(o, stop_gradient=not jnp.issubdtype(o.dtype, jnp.inexact))
        if not t.stop_gradient:
            t._grad_node = node
            t._output_index = i
        tensors.append(t)

    if _flags.get_flag("check_nan_inf"):
        _check_nan_inf(name, [t._value for t in tensors])
    if _flags.get_flag("benchmark"):
        jax.block_until_ready([t._value for t in tensors])
    return tensors[0] if single else tuple(tensors)


def _wrap_outputs(name, out, stop_gradient: bool):
    single = not isinstance(out, (tuple, list))
    outs = (out,) if single else out
    tensors = [Tensor(o, stop_gradient=True) for o in outs]
    if _flags.get_flag("check_nan_inf"):
        _check_nan_inf(name, [t._value for t in tensors])
    return tensors[0] if single else tuple(tensors)


def def_op(name: str | None = None, nondiff: bool = False):
    """Decorator turning a jax function into a registered eager op.

    The decorated function's positional args may be Tensors (differentiable
    data inputs); keyword args are static attributes (~ OpDesc attrs).
    """
    def deco(fn):
        opname = name or fn.__name__

        @functools.wraps(fn)
        def api(*args, **kwargs):
            return apply_op(opname, fn, *args, nondiff=nondiff, **kwargs)

        api.raw_fn = fn
        api.op_name = opname
        OP_REGISTRY[opname] = api
        return api
    return deco
