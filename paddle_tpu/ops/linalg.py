"""Linear algebra ops.

~ python/paddle/tensor/linalg.py over phi matmul/blas kernels
(paddle/phi/kernels/matmul_kernel.h, funcs/blas/). Matmuls are the MXU path:
we route through jnp.matmul/einsum with configurable precision and leave
tiling to XLA.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flags as _flags
from .dispatch import def_op, apply_op


def _precision():
    p = _flags.get_flag("tpu_matmul_precision")
    return None if p == "default" else p


@def_op("matmul")
def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y, precision=_precision())


@def_op("mm")
def mm(x, y):
    return jnp.matmul(x, y, precision=_precision())


@def_op("bmm")
def bmm(x, y):
    return jnp.matmul(x, y, precision=_precision())


@def_op("dot")
def dot(x, y):
    return jnp.sum(x * y, axis=-1)


@def_op("mv")
def mv(x, vec):
    return jnp.matmul(x, vec, precision=_precision())


def einsum(equation, *operands):
    return apply_op(
        "einsum",
        lambda *ops: jnp.einsum(equation, *ops, precision=_precision()),
        *operands)


@def_op("norm")
def norm(x, p="fro", axis=None, keepdim=False):
    if p == "fro":
        if axis is None:
            return jnp.sqrt(jnp.sum(jnp.square(x)))
        return jnp.linalg.norm(x, ord="fro" if isinstance(axis, (list, tuple)) else None,
                               axis=tuple(axis) if isinstance(axis, list) else axis,
                               keepdims=keepdim)
    if p == np.inf or p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == -np.inf or p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.sum(jnp.abs(x) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)


@def_op("cross")
def cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=int(axis) if axis is not None else -1)


@def_op("t")
def t(x):
    return x.T


@def_op("cholesky")
def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


@def_op("inverse")
def inverse(x):
    return jnp.linalg.inv(x)


@def_op("pinv")
def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@def_op("det")
def det(x):
    return jnp.linalg.det(x)


@def_op("slogdet")
def slogdet(x):
    sign, logabs = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logabs])


@def_op("matrix_power")
def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, int(n))


@def_op("matrix_rank", nondiff=True)
def matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol)


def qr(x, mode="reduced"):
    return apply_op("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x)


def svd(x, full_matrices=False):
    return apply_op(
        "svd", lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)), x)


def eigh(x, UPLO="L"):
    return apply_op("eigh", lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), x)


def eig(x):
    # jnp.linalg.eig is CPU-only; run on host (mirrors phi eig which is CPU)
    import numpy.linalg as la

    def _eig(a):
        w, v = la.eig(np.asarray(a))
        return jnp.asarray(w), jnp.asarray(v)
    return apply_op("eig", _eig, x, nondiff=True)


@def_op("eigvalsh")
def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@def_op("solve")
def solve(x, y):
    return jnp.linalg.solve(x, y)


@def_op("triangular_solve")
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


@def_op("cholesky_solve")
def cholesky_solve(x, y, upper=False):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


def lstsq(x, y, rcond=None, driver=None):
    return apply_op(
        "lstsq", lambda a, b: tuple(jnp.linalg.lstsq(a, b, rcond=rcond)), x, y,
        nondiff=True)


def lu(x, pivot=True):
    """~ paddle.linalg.lu: packed LU factors + 1-based LAPACK pivots
    (python/paddle/tensor/linalg.py lu)."""
    def fn(a):
        lu_packed, piv = jax.scipy.linalg.lu_factor(a)
        return lu_packed, (piv + 1).astype(jnp.int32)
    return apply_op("lu", fn, x, nondiff=True)


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True):
    """~ paddle.linalg.lu_unpack: (P, L, U) from packed LU + pivots."""
    def fn(lu_packed, piv):
        m = lu_packed.shape[-2]
        n = lu_packed.shape[-1]
        k = min(m, n)
        L = jnp.tril(lu_packed[..., :, :k], -1) + jnp.eye(m, k,
                                                          dtype=lu_packed.dtype)
        U = jnp.triu(lu_packed[..., :k, :])
        # P from the ipiv swap sequence (1-based)
        def perm_of(piv1):
            def body(i, perm):
                j = piv1[i] - 1
                pi = perm[i]
                pj = perm[j]
                return perm.at[i].set(pj).at[j].set(pi)
            perm0 = jnp.arange(m)
            return jax.lax.fori_loop(0, piv1.shape[0], body, perm0)
        perm = perm_of(piv) if piv.ndim == 1 else jax.vmap(perm_of)(piv)
        P = jax.nn.one_hot(perm, m, dtype=lu_packed.dtype)
        # rows of P: P[i, perm[i]] = 1 -> P @ A permutes; paddle wants
        # A = P L U, i.e. P is the inverse permutation matrix
        P = jnp.swapaxes(P, -1, -2)
        return P, L, U
    return apply_op("lu_unpack", fn, x, y, nondiff=True)


def eigvals(x):
    """~ paddle.linalg.eigvals (host eig; XLA has no general eig)."""
    def fn(a):
        host = np.linalg.eigvals(np.asarray(a))
        return jnp.asarray(host)
    return apply_op("eigvals", fn, x, nondiff=True)


def cond(x, p=None):
    """~ paddle.linalg.cond — condition number under norm p."""
    def fn(a):
        if p is None or p == 2:
            s = jnp.linalg.svd(a, compute_uv=False)
            return s[..., 0] / s[..., -1]
        if p == "fro":
            return (jnp.linalg.norm(a, "fro", axis=(-2, -1))
                    * jnp.linalg.norm(jnp.linalg.inv(a), "fro", axis=(-2, -1)))
        if p == "nuc":
            s = jnp.linalg.svd(a, compute_uv=False)
            si = jnp.linalg.svd(jnp.linalg.inv(a), compute_uv=False)
            return jnp.sum(s, -1) * jnp.sum(si, -1)
        if p in (np.inf, float("inf"), -np.inf, float("-inf"), 1, -1, 2, -2):
            return (jnp.linalg.norm(a, p, axis=(-2, -1))
                    * jnp.linalg.norm(jnp.linalg.inv(a), p, axis=(-2, -1)))
        raise ValueError(f"unsupported norm order {p}")
    return apply_op("cond", fn, x)


inv = inverse


@def_op("corrcoef")
def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


@def_op("cov")
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


@def_op("histogram", nondiff=True)
def histogram(input, bins=100, min=0, max=0):
    lo, hi = (None, None) if (min == 0 and max == 0) else (min, max)
    hist, _ = jnp.histogram(input, bins=int(bins),
                            range=None if lo is None else (lo, hi))
    return hist


@def_op("matrix_transpose")
def matrix_transpose(x):
    return jnp.swapaxes(x, -1, -2)
