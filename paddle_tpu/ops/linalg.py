"""Linear algebra ops.

~ python/paddle/tensor/linalg.py over phi matmul/blas kernels
(paddle/phi/kernels/matmul_kernel.h, funcs/blas/). Matmuls are the MXU path:
we route through jnp.matmul/einsum with configurable precision and leave
tiling to XLA.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flags as _flags
from .dispatch import def_op, apply_op


def _precision():
    p = _flags.get_flag("tpu_matmul_precision")
    return None if p == "default" else p


@def_op("matmul")
def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y, precision=_precision())


@def_op("mm")
def mm(x, y):
    return jnp.matmul(x, y, precision=_precision())


@def_op("bmm")
def bmm(x, y):
    return jnp.matmul(x, y, precision=_precision())


@def_op("dot")
def dot(x, y):
    return jnp.sum(x * y, axis=-1)


@def_op("mv")
def mv(x, vec):
    return jnp.matmul(x, vec, precision=_precision())


def einsum(equation, *operands):
    return apply_op(
        "einsum",
        lambda *ops: jnp.einsum(equation, *ops, precision=_precision()),
        *operands)


@def_op("norm")
def norm(x, p="fro", axis=None, keepdim=False):
    if p == "fro":
        if axis is None:
            return jnp.sqrt(jnp.sum(jnp.square(x)))
        return jnp.linalg.norm(x, ord="fro" if isinstance(axis, (list, tuple)) else None,
                               axis=tuple(axis) if isinstance(axis, list) else axis,
                               keepdims=keepdim)
    if p == np.inf or p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == -np.inf or p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.sum(jnp.abs(x) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)


@def_op("cross")
def cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=int(axis) if axis is not None else -1)


@def_op("t")
def t(x):
    return x.T


@def_op("cholesky")
def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


@def_op("inverse")
def inverse(x):
    return jnp.linalg.inv(x)


@def_op("pinv")
def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@def_op("det")
def det(x):
    return jnp.linalg.det(x)


@def_op("slogdet")
def slogdet(x):
    sign, logabs = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logabs])


@def_op("matrix_power")
def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, int(n))


@def_op("matrix_rank", nondiff=True)
def matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol)


def qr(x, mode="reduced"):
    return apply_op("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x)


def svd(x, full_matrices=False):
    return apply_op(
        "svd", lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)), x)


def eigh(x, UPLO="L"):
    return apply_op("eigh", lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), x)


def eig(x):
    # jnp.linalg.eig is CPU-only; run on host (mirrors phi eig which is CPU)
    import numpy.linalg as la

    def _eig(a):
        w, v = la.eig(np.asarray(a))
        return jnp.asarray(w), jnp.asarray(v)
    return apply_op("eig", _eig, x, nondiff=True)


@def_op("eigvalsh")
def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@def_op("solve")
def solve(x, y):
    return jnp.linalg.solve(x, y)


@def_op("triangular_solve")
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


@def_op("cholesky_solve")
def cholesky_solve(x, y, upper=False):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


def lstsq(x, y, rcond=None, driver=None):
    return apply_op(
        "lstsq", lambda a, b: tuple(jnp.linalg.lstsq(a, b, rcond=rcond)), x, y,
        nondiff=True)


def lu(x, pivot=True):
    return apply_op("lu", lambda a: tuple(jax.scipy.linalg.lu(a)[:2]), x,
                    nondiff=True)


@def_op("corrcoef")
def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


@def_op("cov")
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


@def_op("histogram", nondiff=True)
def histogram(input, bins=100, min=0, max=0):
    lo, hi = (None, None) if (min == 0 and max == 0) else (min, max)
    hist, _ = jnp.histogram(input, bins=int(bins),
                            range=None if lo is None else (lo, hi))
    return hist


@def_op("matrix_transpose")
def matrix_transpose(x):
    return jnp.swapaxes(x, -1, -2)
