"""Inference deployment.

~ paddle/fluid/inference/ AnalysisPredictor (analysis_predictor.h:93):
load optimized artifact → run with zero-copy tensors. TPU-native: the
"analysis + pass pipeline" is XLA compilation at export time (jit.save
freezes weights into a jax.export module); Predictor is the NaiveExecutor
analog executing that artifact. Per-thread serving clones share the loaded
executable and frozen weights (the reference shares weights via Scope,
analysis_predictor.h Clone); input handles hold device arrays so repeated
run() calls do not re-copy unchanged inputs. TensorRT/Lite/ONNX engine
slots are intentionally absent (SURVEY.md §7 non-goals) — XLA is the
engine.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import List, Optional

import jax
import numpy as np

from ..core.tensor import Tensor
from ..jit import load as _jit_load


class Config:
    """~ paddle_infer.Config (inference/api/paddle_analysis_config.h).

    Knobs that have an XLA meaning are honored (memory optim → block
    until-ready elision; device selection); graph-level IR toggles are
    no-ops by design because the artifact was already optimized by XLA at
    export time — recorded so summary() reports them honestly.
    """

    def __init__(self, model_path: Optional[str] = None,
                 params_path: Optional[str] = None):
        self.model_path = model_path
        self.params_path = params_path
        self._threads = 1
        self._device = "tpu" if any(
            d.platform != "cpu" for d in jax.devices()) else "cpu"
        self._memory_optim = False
        self._ir_optim = True
        self._glog = True
        self._profile = False

    # -- devices ---------------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # accel is implicit on TPU; kept for source compatibility
        self._device = "tpu"

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device != "cpu"

    # -- execution -------------------------------------------------------
    def set_cpu_math_library_num_threads(self, n):
        self._threads = int(n)

    def cpu_math_library_num_threads(self):
        return self._threads

    def enable_memory_optim(self, flag=True):
        self._memory_optim = bool(flag)

    def memory_optim_enabled(self):
        return self._memory_optim

    def switch_ir_optim(self, flag=True):
        self._ir_optim = bool(flag)

    def ir_optim(self):
        return self._ir_optim

    def disable_glog_info(self):
        self._glog = False

    def glog_info_disabled(self):
        return not self._glog

    def enable_profile(self):
        self._profile = True

    def summary(self) -> str:
        rows = [("model_path", self.model_path),
                ("device", self._device),
                ("cpu_math_threads", self._threads),
                ("memory_optim", self._memory_optim),
                ("ir_optim (XLA at export)", self._ir_optim),
                ("profile", self._profile)]
        w = max(len(k) for k, _ in rows)
        return "\n".join(f"{k.ljust(w)}  {v}" for k, v in rows)


class Predictor:
    """~ paddle_infer.Predictor over a jit.save artifact."""

    def __init__(self, config_or_path, _shared=None):
        self._config = (config_or_path
                        if isinstance(config_or_path, Config) else None)
        if _shared is not None:
            # clone: share executable + weights, private IO buffers
            self._layer = _shared
        else:
            path = (config_or_path.model_path
                    if isinstance(config_or_path, Config) else config_or_path)
            if path.endswith(".pdmodel") or path.endswith(".pdiparams"):
                path = path.rsplit(".", 1)[0]
            self._layer = _jit_load(path)
        self._inputs: List = []
        self._outputs: List = []
        self._lock = threading.Lock()

    # -- signature -------------------------------------------------------
    def _n_inputs(self) -> int:
        exp = getattr(self._layer, "_exported", None)
        if exp is not None:
            return len(exp.in_avals)
        return 8

    def get_input_names(self):
        return [f"x{i}" for i in range(self._n_inputs())]

    def get_input_handle(self, name):
        idx = int(name[1:]) if name[1:].isdigit() else 0
        return _IOHandle(self, idx)

    def get_output_names(self):
        return [f"out{i}" for i in range(len(self._outputs) or 1)]

    def get_output_handle(self, name):
        return _OutHandle(self, int(name[3:]) if name[3:].isdigit() else 0)

    # -- execution -------------------------------------------------------
    def run(self, inputs: Optional[List] = None):
        if inputs is not None:
            self._inputs = [
                x._value if isinstance(x, Tensor) else np.asarray(x)
                for x in inputs]
        with self._lock:
            outs = self._layer(*[Tensor(x) for x in self._inputs])
        if not isinstance(outs, (tuple, list)):
            outs = [outs]
        if self._config is not None and self._config.memory_optim_enabled():
            # keep device arrays; host copy deferred to copy_to_cpu
            self._outputs = [o._value for o in outs]
        else:
            self._outputs = [o.numpy() for o in outs]
        return [np.asarray(o) for o in self._outputs]

    def clone(self) -> "Predictor":
        """Weight/executable-sharing clone for per-thread serving
        (~ AnalysisPredictor::Clone sharing the Scope)."""
        c = Predictor(self._config or "", _shared=self._layer)
        return c

    def try_shrink_memory(self):
        import gc
        gc.collect()


class _IOHandle:
    """Input handle; holds the array until run() (zero-copy for device
    arrays passed via share_external_data)."""

    def __init__(self, pred, idx):
        self.pred = pred
        self.idx = idx
        self._shape = None

    def _store(self, arr):
        while len(self.pred._inputs) <= self.idx:
            self.pred._inputs.append(None)
        self.pred._inputs[self.idx] = arr

    def copy_from_cpu(self, arr):
        a = np.asarray(arr)
        if self._shape is not None:
            a = a.reshape(self._shape)
        self._store(a)

    def share_external_data(self, arr):
        # device array stays on device — no host round trip
        self._store(arr._value if isinstance(arr, Tensor) else arr)

    def reshape(self, shape):
        self._shape = tuple(shape)


class _OutHandle:
    def __init__(self, pred, idx):
        self.pred = pred
        self.idx = idx

    def copy_to_cpu(self):
        return np.asarray(self.pred._outputs[self.idx])

    def shape(self):
        return tuple(self.pred._outputs[self.idx].shape)


class PredictorPool:
    """~ paddle_infer::services::PredictorPool — one loaded artifact,
    N weight-sharing clones for worker threads."""

    def __init__(self, config: Config, size: int = 1):
        self._main = Predictor(config)
        self._preds = [self._main] + [self._main.clone()
                                      for _ in range(max(0, size - 1))]

    def retrieve(self, idx: int) -> Predictor:
        return self._preds[idx]

    def __len__(self):
        return len(self._preds)


@dataclasses.dataclass
class BatchingConfig:
    """The request-coalescing knobs shared by BOTH batchers: how many
    requests one flush may gather (``max_batch``) and how long the
    oldest waiting request may sit before a partial batch flushes
    anyway (``max_delay_ms``).

    ``DynamicBatcher`` (request/response predictors) and the
    continuous-batching ``paddle_tpu.serving.ServingEngine`` (token
    streams) both take this as their admission config, so the two
    batching layers cannot grow divergent knob sets.
    """

    max_batch: int = 32
    max_delay_ms: float = 2.0

    @property
    def max_delay(self) -> float:
        """max_delay_ms in seconds (the unit the wait loops use)."""
        return self.max_delay_ms / 1e3


class DynamicBatcher:
    """Serving-side request coalescing (~ the reference serving stack's
    request batching in front of AnalysisPredictor).

    Concurrent ``infer()`` calls gather into ONE batch executed on a
    single Predictor call — the TPU-native serving shape: the MXU wants
    few large matmuls, and XLA compiles one executable per batch size, so
    gathered batches PAD UP to power-of-two buckets (<= max_batch) to
    keep the compiled-shape set logarithmic. Results are split back per
    request; padding rows are dropped. A lone request never waits past
    ``max_delay_ms``: the flush timer fires and it rides a batch of one.
    """

    def __init__(self, predictor: Predictor, max_batch: int | None = None,
                 max_delay_ms: float | None = None,
                 config: BatchingConfig | None = None):
        config = config or BatchingConfig()
        if max_batch is not None:
            config = dataclasses.replace(config, max_batch=max_batch)
        if max_delay_ms is not None:
            config = dataclasses.replace(config,
                                         max_delay_ms=max_delay_ms)
        self.predictor = predictor
        self.config = config
        self.max_batch = config.max_batch
        self.max_delay = config.max_delay
        self._pending: List = []
        self._cv = threading.Condition()
        self._stopped = False
        self._runs = 0  # underlying predictor.run calls (telemetry/tests)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def infer(self, inputs: List) -> List[np.ndarray]:
        """Submit one request (list of arrays, leading dim = this
        request's rows); blocks until its slice of the batched result is
        ready."""
        arrs = [np.asarray(x._value if isinstance(x, Tensor) else x)
                for x in inputs]
        done = threading.Event()
        # signature groups batch assembly: only shape/dtype-compatible
        # requests coalesce, so one malformed request can't poison the
        # valid requests that happened to land in the same window
        sig = tuple((a.shape[1:], str(a.dtype)) for a in arrs)
        slot = {"inputs": arrs, "rows": arrs[0].shape[0], "sig": sig,
                "done": done, "out": None, "err": None}
        with self._cv:
            if self._stopped:
                raise RuntimeError("DynamicBatcher is shut down")
            self._pending.append(slot)
            self._cv.notify_all()
        done.wait()
        if slot["err"] is not None:
            raise slot["err"]
        return slot["out"]

    @staticmethod
    def _bucket(n: int, cap: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return min(b, max(cap, n))

    def _loop(self):
        while True:
            with self._cv:
                while not self._pending and not self._stopped:
                    self._cv.wait()
                if self._stopped and not self._pending:
                    return
                deadline = time.perf_counter() + self.max_delay
                while (sum(s["rows"] for s in self._pending) < self.max_batch
                       and not self._stopped):
                    left = deadline - time.perf_counter()
                    if left <= 0:
                        break
                    self._cv.wait(timeout=left)
                sig = self._pending[0]["sig"]
                batch, taken, rest = [], 0, []
                for s in self._pending:
                    if s["sig"] == sig and (
                            not batch
                            or taken + s["rows"] <= self.max_batch):
                        # the first request is always taken (even if its
                        # own rows exceed max_batch); later ones only
                        # while the budget holds
                        batch.append(s)
                        taken += s["rows"]
                    else:
                        rest.append(s)
                self._pending = rest
            try:
                n_in = len(batch[0]["inputs"])
                cat = [np.concatenate([s["inputs"][i] for s in batch])
                       for i in range(n_in)]
                rows = cat[0].shape[0]
                padded = self._bucket(rows, self.max_batch)
                if padded > rows:
                    cat = [np.concatenate(
                        [c, np.repeat(c[-1:], padded - rows, axis=0)])
                        for c in cat]
                outs = self.predictor.run(cat)
                self._runs += 1
                off = 0
                for s in batch:
                    s["out"] = [o[off:off + s["rows"]] for o in outs]
                    off += s["rows"]
            except Exception as e:  # noqa: BLE001 — delivered per request
                for s in batch:
                    s["err"] = e
            for s in batch:
                s["done"].set()

    def shutdown(self):
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self._thread.join(timeout=10)


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def get_version() -> str:
    from .. import __version__
    return __version__
