"""Inference deployment.

~ paddle/fluid/inference/ AnalysisPredictor (analysis_predictor.h:93):
load optimized artifact → run with zero-copy tensors. TPU-native: the
"analysis + pass pipeline" is XLA compilation at export time (jit.save
freezes weights into a jax.export module); Predictor is the NaiveExecutor
analog executing that artifact. TensorRT/Lite/ONNX engine slots are
intentionally absent (SURVEY.md §7 non-goals) — XLA is the engine.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..jit import load as _jit_load


class Config:
    """~ paddle_infer.Config (API-parity surface)."""

    def __init__(self, model_path: str | None = None,
                 params_path: str | None = None):
        self.model_path = model_path
        self._threads = 1

    def set_cpu_math_library_num_threads(self, n):
        self._threads = n

    def enable_use_gpu(self, *a, **kw):  # accel is implicit on TPU
        pass

    def disable_glog_info(self):
        pass

    def switch_ir_optim(self, flag=True):  # XLA always optimizes
        pass


class Predictor:
    """~ paddle_infer.Predictor over a jit.save artifact."""

    def __init__(self, config_or_path):
        path = (config_or_path.model_path
                if isinstance(config_or_path, Config) else config_or_path)
        if path.endswith(".pdmodel") or path.endswith(".pdiparams"):
            path = path.rsplit(".", 1)[0]
        self._layer = _jit_load(path)
        self._inputs: List[np.ndarray] = []

    def get_input_names(self):
        return [f"x{i}" for i in range(8)]

    def get_input_handle(self, name):
        return _IOHandle(self, int(name[1:]) if name[1:].isdigit() else 0)

    def run(self, inputs: Optional[List] = None):
        if inputs is not None:
            self._inputs = [np.asarray(
                x.numpy() if isinstance(x, Tensor) else x) for x in inputs]
        outs = self._layer(*[Tensor(x) for x in self._inputs])
        if isinstance(outs, (tuple, list)):
            self._outputs = [o.numpy() for o in outs]
        else:
            self._outputs = [outs.numpy()]
        return self._outputs

    def get_output_names(self):
        return [f"out{i}" for i in range(len(getattr(self, "_outputs", [0])))]

    def get_output_handle(self, name):
        return _OutHandle(self, int(name[3:]) if name[3:].isdigit() else 0)


class _IOHandle:
    def __init__(self, pred, idx):
        self.pred = pred
        self.idx = idx

    def copy_from_cpu(self, arr):
        while len(self.pred._inputs) <= self.idx:
            self.pred._inputs.append(None)
        self.pred._inputs[self.idx] = np.asarray(arr)

    def reshape(self, shape):
        pass


class _OutHandle:
    def __init__(self, pred, idx):
        self.pred = pred
        self.idx = idx

    def copy_to_cpu(self):
        return self.pred._outputs[self.idx]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
