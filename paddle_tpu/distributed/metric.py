"""Distributed metrics (PS-mode global metric aggregation).

~ python/paddle/distributed/metric/metrics.py (init_metric :26,
print_metric :98, print_auc :116 — metrics accumulated in distributed
table memory and reduced across trainers). TPU-native: local metric
state (AUC buckets, counts) lives in numpy; `all-reduce` across workers
rides the eager collective API when multi-process, identity otherwise.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

__all__ = ["DistributedAuc", "init_metric", "print_metric", "print_auc"]

_registry: Dict[str, "DistributedAuc"] = {}


class DistributedAuc:
    """Bucketed global AUC (~ the reference's distributed AUC table:
    positive/negative histograms over prediction buckets, merged across
    workers before the trapezoid integration)."""

    def __init__(self, n_buckets: int = 2 ** 12):
        self.n_buckets = n_buckets
        self._pos = np.zeros(n_buckets, np.float64)
        self._neg = np.zeros(n_buckets, np.float64)

    def update(self, preds, labels):
        preds = np.clip(np.asarray(preds, np.float64).reshape(-1), 0, 1)
        labels = np.asarray(labels).reshape(-1)
        idx = np.minimum((preds * self.n_buckets).astype(np.int64),
                         self.n_buckets - 1)
        np.add.at(self._pos, idx[labels == 1], 1)
        np.add.at(self._neg, idx[labels == 0], 1)

    def _merged(self):
        """All-reduce the histograms across workers when distributed.
        Counts reduce as two f32 limbs (lo = count mod 2^20, hi =
        count // 2^20) — a single f32 silently rounds counts past 2^24,
        skewing the global AUC on large jobs."""
        from . import collective as C
        if C._multi_process():
            from ..core.tensor import Tensor
            import jax.numpy as jnp
            both = np.stack([self._pos, self._neg])
            hi = np.floor(both / 2 ** 20)
            lo = both - hi * 2 ** 20
            buf = Tensor(jnp.asarray(np.stack([hi, lo]).astype(np.float32)))
            C.all_reduce(buf)
            merged = np.asarray(buf.numpy(), np.float64)
            total = merged[0] * 2 ** 20 + merged[1]
            return total[0], total[1]
        return self._pos, self._neg

    def value(self) -> float:
        pos, neg = self._merged()
        # integrate from the highest bucket down (descending threshold)
        tp = np.cumsum(pos[::-1])
        fp = np.cumsum(neg[::-1])
        P, N = tp[-1], fp[-1]
        if P == 0 or N == 0:
            return 0.5
        tpr = np.concatenate([[0.0], tp / P])
        fpr = np.concatenate([[0.0], fp / N])
        trapz = getattr(np, "trapezoid", None) or np.trapz  # numpy<2.0
        return float(trapz(tpr, fpr))

    def reset(self):
        self._pos[:] = 0
        self._neg[:] = 0


def init_metric(metric_ptr=None, name: str = "auc", method: str = "auc",
                n_buckets: int = 2 ** 12, **kw) -> DistributedAuc:
    m = DistributedAuc(n_buckets)
    _registry[name] = m
    return m


def get_metric(name: str = "auc") -> Optional[DistributedAuc]:
    return _registry.get(name)


def print_metric(metric_ptr=None, name: str = "auc") -> str:
    m = _registry.get(name)
    msg = f"{name}: {m.value():.6f}" if m else f"{name}: <uninitialized>"
    print(msg)
    return msg


def print_auc(metric_ptr=None, is_day: bool = False,
              phase: str = "all") -> str:
    return print_metric(name="auc")
