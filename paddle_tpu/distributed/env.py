"""Distributed environment: rank/world discovery + runtime init.

~ python/paddle/distributed/parallel.py (init_parallel_env:91, ParallelEnv)
and the launch env contract (launch/controllers/collective.py:83-91).
TPU-native rendezvous: ``jax.distributed.initialize`` (coordinator service)
replaces TCPStore + NCCL unique-id exchange.

Env contract (compatible naming):
  PADDLE_MASTER / PADDLE_COORDINATOR : "host:port" coordinator address
  PADDLE_GLOBAL_RANK | PADDLE_TRAINER_ID : process index
  PADDLE_WORLD_SIZE | PADDLE_TRAINERS_NUM : process count
  PADDLE_LOCAL_RANK : local process index
"""
from __future__ import annotations

import os
import threading

import jax

_lock = threading.Lock()
_initialized = False


def _env_int(*names, default=0):
    for n in names:
        v = os.environ.get(n)
        if v is not None:
            return int(v)
    return default


def get_rank() -> int:
    if _initialized or jax.process_count() > 1:
        return jax.process_index()
    return _env_int("PADDLE_GLOBAL_RANK", "PADDLE_TRAINER_ID", default=0)


def get_world_size() -> int:
    if _initialized or jax.process_count() > 1:
        return jax.process_count()
    return _env_int("PADDLE_WORLD_SIZE", "PADDLE_TRAINERS_NUM", default=1)


def get_local_rank() -> int:
    return _env_int("PADDLE_LOCAL_RANK", default=0)


def is_initialized() -> bool:
    return _initialized


def init_parallel_env():
    """~ paddle.distributed.init_parallel_env (parallel.py:91).

    Multi-process: connects to the coordinator (jax.distributed.initialize).
    Single-process: no-op — the mesh over local devices is the parallel env.
    """
    global _initialized
    with _lock:
        if _initialized:
            return ParallelEnv()
        coord = os.environ.get("PADDLE_MASTER") or \
            os.environ.get("PADDLE_COORDINATOR")
        world = _env_int("PADDLE_WORLD_SIZE", "PADDLE_TRAINERS_NUM", default=1)
        if coord and world > 1:
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=world,
                process_id=_env_int("PADDLE_GLOBAL_RANK", "PADDLE_TRAINER_ID",
                                    default=0))
        _initialized = True
    return ParallelEnv()


class ParallelEnv:
    """~ parallel.py ParallelEnv — env view object."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return get_local_rank()

    @property
    def dev_id(self):
        return get_local_rank()

    @property
    def device_type(self):
        return "tpu"

    @property
    def nranks(self):
        return get_world_size()
