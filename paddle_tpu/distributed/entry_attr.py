"""Sparse-table entry policies + file datasets for the PS data pipeline.

~ python/paddle/distributed/entry_attr.py (ProbabilityEntry:*, CountFilterEntry)
and python/paddle/distributed/fleet/dataset/dataset.py (InMemoryDataset:*,
QueueDataset). The reference's datasets drive C++ DataFeed readers
(framework/data_feed.h) over file lists; here the same API surface feeds the
native threaded batch loader (csrc/batch_loader.cc) / python fallback.
"""
from __future__ import annotations

import os

import numpy as np


class EntryAttr:
    """Base for sparse-embedding entry policies (when a new key is admitted
    to the table)."""

    def _to_attr(self) -> str:
        raise NotImplementedError


class ProbabilityEntry(EntryAttr):
    """Admit new sparse keys with fixed probability."""

    def __init__(self, probability):
        if not 0 < probability <= 1:
            raise ValueError("probability must be in (0, 1]")
        self.probability = probability

    def _to_attr(self):
        return f"probability_entry:{self.probability}"


class CountFilterEntry(EntryAttr):
    """Admit a sparse key once it has been seen >= count times."""

    def __init__(self, count):
        if count < 1:
            raise ValueError("count must be >= 1")
        self.count = count

    def _to_attr(self):
        return f"count_filter_entry:{self.count}"


class ShowClickEntry(EntryAttr):
    """Track show/click stats per key (CTR accessors)."""

    def __init__(self, show_name, click_name):
        self.show_name = show_name
        self.click_name = click_name

    def _to_attr(self):
        return f"show_click_entry:{self.show_name}:{self.click_name}"


class _FileListDataset:
    """Shared file-list plumbing (~ fleet/dataset/dataset.py DatasetBase)."""

    def __init__(self):
        self._filelist = []
        self._batch_size = 1
        self._thread_num = 1
        self._use_var = []
        self._pipe_command = None
        self._parse_fn = None

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command=None, input_type=0, fs_name="", fs_ugi="",
             download_cmd="cat", **kwargs):
        self._batch_size = batch_size
        self._thread_num = thread_num
        self._use_var = use_var or []
        self._pipe_command = pipe_command

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def set_batch_size(self, batch_size):
        self._batch_size = batch_size

    def set_thread(self, thread_num):
        self._thread_num = thread_num

    def set_parse_fn(self, fn):
        """Line -> sample parser (the data_generator role)."""
        self._parse_fn = fn

    def _iter_lines(self):
        for path in self._filelist:
            with open(path) as f:
                for line in f:
                    line = line.rstrip("\n")
                    if not line:
                        continue
                    yield self._parse_fn(line) if self._parse_fn \
                        else line

    def _batches(self, samples):
        buf = []
        for s in samples:
            buf.append(s)
            if len(buf) == self._batch_size:
                yield _stack_batch(buf)
                buf = []
        if buf:
            yield _stack_batch(buf)


def _stack_batch(samples):
    if isinstance(samples[0], (tuple, list)):
        return tuple(np.stack([np.asarray(s[i]) for s in samples])
                     for i in range(len(samples[0])))
    return np.stack([np.asarray(s) for s in samples])


class InMemoryDataset(_FileListDataset):
    """~ fleet InMemoryDataset: load file list into memory, global shuffle,
    then iterate batches."""

    def __init__(self):
        super().__init__()
        self._samples = []

    def load_into_memory(self):
        self._samples = list(self._iter_lines())

    def local_shuffle(self):
        np.random.shuffle(self._samples)

    def global_shuffle(self, fleet=None, thread_num=12):
        # single-host form of the PS global shuffle
        self.local_shuffle()

    def get_memory_data_size(self, fleet=None):
        return len(self._samples)

    def get_shuffle_data_size(self, fleet=None):
        return len(self._samples)

    def release_memory(self):
        self._samples = []

    def __iter__(self):
        return self._batches(iter(self._samples))


class QueueDataset(_FileListDataset):
    """~ fleet QueueDataset: streaming file reader (no in-memory buffer)."""

    def __iter__(self):
        return self._batches(self._iter_lines())


class ParallelMode:
    """~ python/paddle/distributed/parallel.py ParallelMode enum."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
