"""N-D parallel topology over a jax device mesh.

~ python/paddle/distributed/fleet/base/topology.py
(CommunicateTopology:52, HybridCommunicateGroup:133). The reference builds
NCCL groups per axis; here an axis IS a mesh axis name, and "groups" are
views over the mesh that compiled collectives reference by name. Axis order
follows the reference ["data", "pipe", "sharding", "sep", "model"] with
"expert" available for MoE — outermost axes map to DCN/slower links,
innermost ("model") to ICI neighbors, mirroring how the reference orders
rings for bandwidth (topology.py comment on hybrid order).
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

from . import env as _env

_DEFAULT_ORDER = ["data", "pipe", "sharding", "sep", "model"]

_global_hcg: Optional["HybridCommunicateGroup"] = None
_global_mesh: Optional[Mesh] = None


class CommunicateTopology:
    """~ topology.py:52 — pure rank-coordinate arithmetic."""

    def __init__(self, hybrid_group_names: Sequence[str] = _DEFAULT_ORDER,
                 dims: Sequence[int] = (1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = list(itertools.product(
            *(range(d) for d in self._dims)))
        self._coord2rank = {c: i for i, c in enumerate(self.coordinate)}

    def get_hybrid_group_names(self) -> List[str]:
        return self._parallel_names

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs) -> int:
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank: int):
        return self.coordinate[rank]

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        axis = self._parallel_names.index(axis_name)
        return [r for r, c in enumerate(self.coordinate) if c[axis] == index]

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """All groups along ``axis_name`` (each = ranks varying only there)."""
        axis = self._parallel_names.index(axis_name)
        other = [self._dims[i] for i in range(len(self._dims)) if i != axis]
        comm = []
        for fixed in itertools.product(*(range(d) for d in other)):
            ranks = []
            for v in range(self._dims[axis]):
                coord = list(fixed)
                coord.insert(axis, v)
                ranks.append(self._coord2rank[tuple(coord)])
            comm.append(ranks)
        return comm

    def get_rank_from_stage(self, global_rank: int, **kwargs) -> int:
        coord = list(self.get_coord(global_rank))
        for k, v in kwargs.items():
            coord[self._parallel_names.index(k)] = v
        return self._coord2rank[tuple(coord)]


class ParallelGroup:
    """Group view (~ paddle.distributed.collective.Group): an axis slice of
    the mesh. Compiled collectives reference it by axis name."""

    def __init__(self, ranks: List[int], rank: int, axis_name: str,
                 group_id: int = 0):
        self.ranks = ranks
        self.nranks = len(ranks)
        self.axis_name = axis_name
        self.id = group_id
        self._rank_in_group = ranks.index(rank) if rank in ranks else -1

    @property
    def rank(self):
        return self._rank_in_group

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return (f"ParallelGroup(axis={self.axis_name}, ranks={self.ranks}, "
                f"rank={self._rank_in_group})")


def build_mesh(dims: Dict[str, int], devices=None) -> Mesh:
    """Create a named jax Mesh for the hybrid topology.

    Axis order: given dict order (callers pass reference order dp,pp,sharding,
    sep,mp so that 'model' lands innermost = ICI-closest).
    Axes of size 1 are kept — pjit specs can always name them.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    shape = tuple(dims.values())
    total = int(np.prod(shape))
    if total != devices.size:
        raise ValueError(
            f"topology {dims} needs {total} devices, have {devices.size}")
    return Mesh(devices.reshape(shape), tuple(dims.keys()))


def set_global_mesh(mesh: Mesh):
    global _global_mesh
    _global_mesh = mesh


def get_global_mesh() -> Optional[Mesh]:
    return _global_mesh


class HybridCommunicateGroup:
    """~ topology.py HybridCommunicateGroup:133.

    Holds the CommunicateTopology + the jax Mesh; exposes the reference's
    full group-getter API surface (get_model_parallel_group etc.,
    topology.py:292-330).
    """

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self.global_rank = _env.get_rank()
        self.nranks = topology.world_size()

        self._dp_degree = self._get_dim("data")
        self._pp_degree = self._get_dim("pipe")
        self._sharding_degree = self._get_dim("sharding")
        self._sep_degree = self._get_dim("sep")
        self._mp_degree = self._get_dim("model")

        # device mesh (only when the process can see enough devices —
        # multi-host meshes are built from global devices)
        self.mesh = None
        try:
            n_dev = len(jax.devices())
            if self.nranks in (1, n_dev) or self.nranks == jax.process_count():
                dims = {"data": self._dp_degree, "pipe": self._pp_degree,
                        "sharding": self._sharding_degree,
                        "sep": self._sep_degree, "model": self._mp_degree}
                if self.nranks <= n_dev:
                    self.mesh = build_mesh(
                        dims, np.asarray(jax.devices())[:self.nranks])
                    set_global_mesh(self.mesh)
        except Exception:
            self.mesh = None

        self._groups = {}
        for name in self._topo.get_hybrid_group_names():
            self._groups[name] = self._make_group(name)

    def _get_dim(self, name):
        try:
            return self._topo.get_dim(name)
        except ValueError:
            return 1

    def _make_group(self, axis_name) -> ParallelGroup:
        for ranks in self._topo.get_comm_list(axis_name):
            if self.global_rank in ranks:
                return ParallelGroup(ranks, self.global_rank, axis_name)
        return ParallelGroup([self.global_rank], self.global_rank, axis_name)

    def topology(self):
        return self._topo

    def get_parallel_mode(self):
        # ~ topology.py:203 — returns the dominant mode string
        if self._mp_degree > 1 or self._pp_degree > 1:
            return "hybrid"
        if self._sharding_degree > 1:
            return "sharding_parallel"
        if self._dp_degree > 1:
            return "data_parallel"
        return "single"

    # ---- data parallel ----
    def get_data_parallel_rank(self):
        return self._groups["data"].rank

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._groups["data"]

    def get_data_parallel_group_src_rank(self):
        return self._groups["data"].ranks[0]

    # ---- model (tensor) parallel ----
    def get_model_parallel_rank(self):
        return self._groups["model"].rank

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._groups["model"]

    def get_model_parallel_group_src_rank(self):
        return self._groups["model"].ranks[0]

    # ---- pipeline parallel ----
    def get_stage_id(self):
        return self._groups["pipe"].rank

    def get_pipe_parallel_rank(self):
        return self._groups["pipe"].rank

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._groups["pipe"]

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    # ---- sharding ----
    def get_sharding_parallel_rank(self):
        return self._groups["sharding"].rank

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._groups["sharding"]

    def get_sharding_parallel_group_src_rank(self):
        return self._groups["sharding"].ranks[0]

    # ---- sep (sequence/context parallel — exceeds the reference) ----
    def get_sep_parallel_rank(self):
        return self._groups["sep"].rank

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._groups["sep"]

    # ---- check/global ----
    def get_global_rank(self):
        return self.global_rank

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank,
                                              pipe=stage_id, **kwargs)


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup):
    global _global_hcg
    _global_hcg = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _global_hcg
