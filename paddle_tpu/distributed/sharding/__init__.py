"""Sharded (ZeRO) data parallelism.

~ python/paddle/distributed/sharding/group_sharded.py
(group_sharded_parallel) over fleet/meta_parallel/sharding/
group_sharded_optimizer_stage2.py:48, group_sharded_stage2.py:49,
group_sharded_stage3.py:58.

TPU-native design: what the reference does with 3k LoC of rank bookkeeping
(param segmentation by size :185, grad slice buffers, re-gather hooks
:393-430) is expressed as sharding SPECS and handed to GSPMD:
  stage 1: optimizer accumulators annotated P('sharding', ...) — states
           sharded, params+grads replicated (reduce_scatter+all_gather
           inserted by XLA).
  stage 2: + grads reduce-scattered (XLA does this automatically once
           states are sharded and the update is compiled — the grad never
           materializes replicated inside the step).
  stage 3: + params annotated P('sharding', ...) — full param sharding;
           all_gather at use is inserted per-layer (the re-gather hooks).
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ...nn.layer.layers import Layer
from ..fleet.meta_parallel.sharding_parallel import ShardingParallel


def _annotate_stage3(model: Layer):
    for p in model.parameters():
        if getattr(p, "sharding_spec", None) is None and p.ndim >= 1:
            # shard the largest dim over 'sharding'
            import numpy as np
            dim = int(np.argmax(p.shape))
            spec = [None] * p.ndim
            spec[dim] = "sharding"
            p.sharding_spec = P(*spec)


class GroupShardedOptimizerStage2:
    """~ group_sharded_optimizer_stage2.py:48: marks the optimizer for
    state sharding. Consumed by Optimizer.step (eager: accumulators get
    NamedShardings over the 'sharding' mesh axis via
    Optimizer._ensure_sharded_state) and by the compiled train-step
    factories (moments laid out P('sharding', ...))."""

    def __init__(self, params, optim, group=None, offload=False, **kw):
        self._optim = optim
        optim._shard_states_axis = "sharding"
        optim._offload_states = bool(offload)
        self.offload = offload

    def __getattr__(self, name):
        return getattr(self._optim, name)


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size
                           =2 ** 23, segment_size=2 ** 20, sync_comm=False):
    """~ python/paddle/distributed/sharding/group_sharded.py:32."""
    assert level in ("os", "os_g", "p_g_os"), f"bad sharding level {level}"
    optimizer._shard_states_axis = "sharding"
    optimizer._offload_states = bool(offload)
    if level == "p_g_os":
        _annotate_stage3(model)
    from ..topology import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    wrapped = ShardingParallel(model, hcg) if hcg else model
    if scaler is not None:
        return wrapped, optimizer, scaler
    return wrapped, optimizer


def save_group_sharded_model(model, output, optimizer=None):
    from ...framework.io import save
    inner = getattr(model, "_layers", model)
    save(inner.state_dict(), output + ".pdparams")
    if optimizer is not None:
        save(optimizer.state_dict(), output + ".pdopt")
