"""Runtime reshard: move a live array between sharding layouts NOW.

~ python/paddle/distributed/auto_parallel/reshard.py:603 (Resharder —
inserts the send/recv/concat/slice ops that convert a tensor between two
dist_attrs at runtime). The TPU-native version needs no op surgery: a
jitted identity with ``out_shardings`` makes XLA's GSPMD partitioner
emit the optimal collective schedule (all-gather / all-to-all /
collective-permute over ICI) for the layout change — including
cross-mesh moves and multi-process global meshes, where every process
calls reshard() with its addressable shards and receives the
addressable shards of the target layout.

The offline sibling (checkpoint/converter.py) reshapes *saved* shards
between topologies; THIS is the live-array path the reference's
Resharder covers, completing the pair.
"""
from __future__ import annotations

import functools as _functools
from typing import Optional, Sequence, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["reshard", "reshard_like"]


def _as_sharding(dst, spec) -> NamedSharding:
    if isinstance(dst, NamedSharding):
        return dst
    if isinstance(dst, Mesh):
        if spec is None:
            raise ValueError("reshard(x, mesh, spec): spec required when "
                             "passing a Mesh")
        if not isinstance(spec, PartitionSpec):
            spec = PartitionSpec(*spec)
        return NamedSharding(dst, spec)
    raise TypeError(f"reshard target must be NamedSharding or Mesh, got "
                    f"{type(dst).__name__}")


def reshard(x, dst: Union[NamedSharding, Mesh],
            spec: Optional[Union[PartitionSpec, Sequence]] = None,
            donate: bool = False):
    """Return ``x`` laid out as ``dst`` (a NamedSharding, or Mesh + spec).

    Works for: same-mesh respec (e.g. row-shard -> col-shard), cross-mesh
    moves over the same device set (e.g. (8,) 'x' -> (2, 4) 'a','b'),
    and multi-process global meshes (each process passes its view of the
    global array; XLA moves bytes over ICI/DCN). Under jit tracing it
    degrades to a sharding constraint on the traced value.

    ``donate``: donate the source buffers (the old layout's memory is
    released as the collective runs — the in-place flavor of the
    reference's Resharder).
    """
    from ..core.tensor import Tensor
    wrap = isinstance(x, Tensor)
    arr = x._value if wrap else x
    sharding = _as_sharding(dst, spec)

    if isinstance(arr, jax.core.Tracer):
        out = jax.lax.with_sharding_constraint(arr, sharding)
        return Tensor(out) if wrap else out

    arr = jax.numpy.asarray(arr)
    if getattr(arr, "sharding", None) is not None \
            and arr.sharding.is_equivalent_to(sharding, arr.ndim):
        return x  # already there: no program, no copy

    out = _jitted_identity(sharding, donate)(arr)
    return Tensor(out) if wrap else out


def _identity(a):
    return a


@_functools.lru_cache(maxsize=256)
def _jitted_identity(sharding: NamedSharding, donate: bool):
    """One cached executable per (sharding, donate): a fresh lambda per
    call would miss jax's compilation cache and re-trace+compile the
    GSPMD program on every training-loop step."""
    return jax.jit(_identity, out_shardings=sharding,
                   donate_argnums=(0,) if donate else ())


def reshard_like(x, other):
    """Reshard ``x`` to the layout of array ``other``."""
    from ..core.tensor import Tensor
    ref = other._value if isinstance(other, Tensor) else other
    if getattr(ref, "sharding", None) is None:
        raise ValueError("reshard_like: reference has no sharding")
    return reshard(x, ref.sharding)
