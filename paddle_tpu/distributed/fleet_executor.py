"""FleetExecutor: actor-style pipelined runtime.

~ paddle/fluid/distributed/fleet_executor/ (Carrier carrier.h:49 scheduling
Interceptor actors interceptor.h:46 over a MessageBus message_bus.h:40, with
ComputeInterceptor/source/sink kinds, TaskNode runtime_graph.cc, and
dist_model.cc as the distributed-inference entry).

TPU-native shape: interceptors are host threads owning one jit-compiled
stage program each; the message bus is in-process queues (the brpc role —
to cross hosts the payloads are jax.Arrays and ride ICI/DCN transfers
implicitly when stages live on different mesh slices). Because XLA dispatch
is async, stage i+1's enqueue overlaps stage i's device compute — the same
pipelining the reference gets from per-interceptor brpc threads.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, List, Optional

__all__ = ["TaskNode", "Interceptor", "ComputeInterceptor", "MessageBus",
           "Carrier", "FleetExecutor", "DistModel", "DistModelConfig"]

_STOP = object()


class TaskNode:
    """~ fleet_executor TaskNode: one schedulable unit of the runtime graph."""

    def __init__(self, rank: int, node_type: str = "Compute",
                 program: Optional[Callable] = None, max_run_times: int = 1,
                 task_id: Optional[int] = None):
        self.rank = rank
        self.node_type = node_type
        self.program = program
        self.max_run_times = max_run_times
        self.task_id = task_id if task_id is not None else rank
        self.downstream: List[int] = []
        self.upstream: List[int] = []

    def add_downstream_task(self, task_id: int, buff_size: int = 2):
        self.downstream.append(task_id)

    def add_upstream_task(self, task_id: int, buff_size: int = 2):
        self.upstream.append(task_id)


class MessageBus:
    """~ message_bus.h:40 — routes messages to interceptor inboxes."""

    def __init__(self):
        self._inboxes: Dict[int, "queue.Queue"] = {}

    def register(self, task_id: int, maxsize: int = 8) -> "queue.Queue":
        q = queue.Queue(maxsize=maxsize)
        self._inboxes[task_id] = q
        return q

    def send(self, dst: int, payload) -> None:
        self._inboxes[dst].put(payload)


class Interceptor:
    """~ interceptor.h:46 — an actor with an inbox loop on its own thread."""

    def __init__(self, task: TaskNode, bus: MessageBus):
        self.task = task
        self.bus = bus
        self.inbox = bus.register(task.task_id)
        self._thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None

    def handle(self, payload):
        raise NotImplementedError

    def _loop(self):
        while True:
            payload = self.inbox.get()
            if payload is _STOP:
                for dst in self.task.downstream:
                    self.bus.send(dst, _STOP)
                break
            try:
                self.handle(payload)
            except BaseException as e:   # propagate to the carrier
                self.error = e
                for dst in self.task.downstream:
                    self.bus.send(dst, _STOP)
                # keep draining the bounded inbox until _STOP arrives, else
                # an upstream blocked in bus.send on this queue never exits
                # and Carrier.run's join() hangs instead of raising
                while True:
                    p = self.inbox.get()
                    if p is _STOP:
                        break
                break

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def join(self):
        if self._thread:
            self._thread.join()


class ComputeInterceptor(Interceptor):
    """~ compute_interceptor.cc: run the stage program, forward the result."""

    def handle(self, payload):
        idx, value = payload
        out = self.task.program(value)
        for dst in self.task.downstream:
            self.bus.send(dst, (idx, out))


class _SinkInterceptor(Interceptor):
    def __init__(self, task, bus, results: dict):
        super().__init__(task, bus)
        self._results = results

    def handle(self, payload):
        idx, value = payload
        self._results[idx] = value


class Carrier:
    """~ carrier.h:49 — owns the interceptors of one runtime graph and
    pushes micro-batches through them."""

    def __init__(self, tasks: List[TaskNode]):
        self.bus = MessageBus()
        self.results: Dict[int, Any] = {}
        self.interceptors: List[Interceptor] = []
        by_id = {t.task_id: t for t in tasks}
        # wire linear order if the graph has no explicit edges
        ordered = sorted(tasks, key=lambda t: t.task_id)
        if not any(t.downstream for t in tasks):
            for a, b in zip(ordered, ordered[1:]):
                a.add_downstream_task(b.task_id)
                b.add_upstream_task(a.task_id)
        sink = TaskNode(rank=-1, node_type="Sink",
                        task_id=max(by_id) + 1 if by_id else 0)
        tails = [t for t in tasks
                 if not t.downstream or all(d == sink.task_id
                                            for d in t.downstream)]
        for t in tails:
            if sink.task_id not in t.downstream:
                t.add_downstream_task(sink.task_id)
        self._head = ordered[0] if ordered else sink
        for t in tasks:
            self.interceptors.append(ComputeInterceptor(t, self.bus))
        self.interceptors.append(
            _SinkInterceptor(sink, self.bus, self.results))
        for ic in self.interceptors:
            ic.start()

    def run(self, microbatches: List[Any]) -> List[Any]:
        self.results.clear()
        for i, mb in enumerate(microbatches):
            self.bus.send(self._head.task_id, (i, mb))
        self.bus.send(self._head.task_id, _STOP)
        for ic in self.interceptors:
            ic.join()
        for ic in self.interceptors:
            if ic.error is not None:
                raise ic.error
        return [self.results[i] for i in sorted(self.results)]


class FleetExecutor:
    """~ fleet_executor.cc: build the runtime graph from stage programs and
    stream micro-batches through the carrier."""

    def __init__(self, stage_programs: List[Callable]):
        self.tasks = [TaskNode(rank=i, program=fn, task_id=i)
                      for i, fn in enumerate(stage_programs)]

    def run(self, microbatches: List[Any]) -> List[Any]:
        carrier = Carrier(list(self.tasks))
        return carrier.run(microbatches)


class DistModelConfig:
    """~ dist_model.h DistModelConfig."""

    def __init__(self, model=None, nranks: int = 1, rank: int = 0,
                 n_microbatches: int = 4):
        self.model = model
        self.nranks = nranks
        self.rank = rank
        self.n_microbatches = n_microbatches


class DistModel:
    """~ dist_model.cc — the distributed inference entry riding the
    fleet-executor runtime: a Layer's sublayers are segmented into
    ``n_stages`` jitted stage programs; micro-batches stream through them
    with overlapped dispatch (hybrid_parallel_inference analog)."""

    def __init__(self, config: DistModelConfig, n_stages: int = 2):
        import jax
        from ..core.tensor import Tensor
        model = config.model
        self._config = config
        subs = [l for l in model.children()]
        if len(subs) < n_stages:
            n_stages = max(1, len(subs))
        per = (len(subs) + n_stages - 1) // n_stages
        segments = [subs[i * per:(i + 1) * per] for i in range(n_stages)]
        segments = [s for s in segments if s]

        def make_stage(layers):
            def stage(x):
                t = Tensor(x)
                from ..autograd import tape as _tape
                with __import__("paddle_tpu").autograd.no_grad():
                    for l in layers:
                        t = l(t)
                return t._value
            return jax.jit(stage)
        self._exe = FleetExecutor([make_stage(s) for s in segments])

    def run(self, inputs) -> list:
        """inputs: full batch (Tensor/array); returns stitched outputs."""
        import jax.numpy as jnp
        import numpy as np
        from ..core.tensor import Tensor
        x = inputs._value if isinstance(inputs, Tensor) else jnp.asarray(inputs)
        n = self._config.n_microbatches
        B = x.shape[0]
        n = min(n, B)
        sizes = [B // n + (1 if i < B % n else 0) for i in range(n)]
        mbs, off = [], 0
        for s in sizes:
            mbs.append(x[off:off + s])
            off += s
        outs = self._exe.run(mbs)
        return Tensor(jnp.concatenate(outs, axis=0))
