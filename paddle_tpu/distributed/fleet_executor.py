"""FleetExecutor: actor-style pipelined runtime.

~ paddle/fluid/distributed/fleet_executor/ (Carrier carrier.h:49 scheduling
Interceptor actors interceptor.h:46 over a MessageBus message_bus.h:40, with
ComputeInterceptor/source/sink kinds, TaskNode runtime_graph.cc, and
dist_model.cc as the distributed-inference entry).

TPU-native shape: interceptors are host threads owning one jit-compiled
stage program each; the message bus is in-process queues (the brpc role —
to cross hosts the payloads are jax.Arrays and ride ICI/DCN transfers
implicitly when stages live on different mesh slices). Because XLA dispatch
is async, stage i+1's enqueue overlaps stage i's device compute — the same
pipelining the reference gets from per-interceptor brpc threads.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, List, Optional

__all__ = ["TaskNode", "Interceptor", "ComputeInterceptor", "MessageBus",
           "DistMessageBus", "Carrier", "DistCarrier", "FleetExecutor",
           "DistModel", "DistModelConfig"]

_STOP = object()


class TaskNode:
    """~ fleet_executor TaskNode: one schedulable unit of the runtime graph."""

    def __init__(self, rank: int, node_type: str = "Compute",
                 program: Optional[Callable] = None, max_run_times: int = 1,
                 task_id: Optional[int] = None):
        self.rank = rank
        self.node_type = node_type
        self.program = program
        self.max_run_times = max_run_times
        self.task_id = task_id if task_id is not None else rank
        self.downstream: List[int] = []
        self.upstream: List[int] = []

    def add_downstream_task(self, task_id: int, buff_size: int = 2):
        self.downstream.append(task_id)

    def add_upstream_task(self, task_id: int, buff_size: int = 2):
        self.upstream.append(task_id)


class MessageBus:
    """~ message_bus.h:40 — routes messages to interceptor inboxes."""

    def __init__(self):
        self._inboxes: Dict[int, "queue.Queue"] = {}

    def register(self, task_id: int, maxsize: int = 8) -> "queue.Queue":
        q = queue.Queue(maxsize=maxsize)
        self._inboxes[task_id] = q
        return q

    def send(self, dst: int, payload) -> None:
        self._inboxes[dst].put(payload)


class DistMessageBus(MessageBus):
    """Cross-process message bus (~ message_bus.h over brpc: InitBus with a
    rank-to-addr table, remote sends serialized over the wire).

    task_to_rank: owner rank of every task id in the runtime graph.
    addrs: rank -> "host:port" listen addresses (the brpc endpoint list).
    Local tasks get in-process queues; sends to remote tasks ship
    length-prefixed pickle frames over cached sockets. Frames arriving
    before the destination inbox registers are buffered.
    """

    _STOP_WIRE = "__fleet_executor_stop__"

    def __init__(self, task_to_rank: Dict[int, int], rank: int,
                 addrs: Dict[int, str]):
        super().__init__()
        import socket
        self._task_to_rank = dict(task_to_rank)
        self._rank = rank
        self._addrs = dict(addrs)
        self._socks: Dict[int, Any] = {}
        self._pending: Dict[int, list] = {}
        self._mu = threading.Lock()
        host, port = addrs[rank].rsplit(":", 1)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, int(port)))
        self._srv.listen(32)
        self._closed = False
        threading.Thread(target=self._accept_loop, daemon=True).start()

    # -- wire ------------------------------------------------------------
    def _accept_loop(self):
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True).start()

    def _reader(self, conn):
        from .ps import _recv_msg
        with conn:
            while True:
                msg = _recv_msg(conn)
                if msg is None:
                    return
                dst, payload = msg
                if payload == self._STOP_WIRE:
                    payload = _STOP
                self._deliver(dst, payload)

    def _deliver(self, dst: int, payload):
        with self._mu:
            q = self._inboxes.get(dst)
            # while a pre-registration backlog exists, new frames must
            # keep appending to it — putting them straight into the
            # fresh queue would let a late frame (worst case: _STOP)
            # overtake earlier buffered data and drop microbatches
            # (the round-3 flake in test_two_process_pipeline)
            if q is None or dst in self._pending:
                self._pending.setdefault(dst, []).append(payload)
                return
        q.put(payload)

    def register(self, task_id: int, maxsize: int = 8) -> "queue.Queue":
        q = super().register(task_id, maxsize)
        while True:
            with self._mu:
                backlog = self._pending.get(task_id)
                if not backlog:
                    # fully drained: drop the key so _deliver goes
                    # direct — order is preserved because frames kept
                    # appending to the backlog until this moment
                    self._pending.pop(task_id, None)
                    break
                p = backlog.pop(0)
            q.put(p)  # outside _mu: a bounded queue may block here
        return q

    def send(self, dst: int, payload) -> None:
        owner = self._task_to_rank.get(dst, self._rank)
        if owner == self._rank:
            self._deliver(dst, payload)
            return
        import socket
        from .ps import _send_msg
        if payload is _STOP:
            payload = self._STOP_WIRE
        else:
            payload = _host_payload(payload)
        # per-destination lock; the (possibly blocking) network write must
        # NOT hold the global _mu — a full remote inbox would otherwise
        # stall _deliver on the reader threads and deadlock both ranks
        with self._mu:
            entry = self._socks.get(owner)
        if entry is None:
            host, port = self._addrs[owner].rsplit(":", 1)
            # Retry refused connections OUTSIDE _mu (holding it would stall
            # _deliver on the reader threads — the deadlock the per-socket
            # locks exist to avoid): a peer rank spawned under machine load
            # may not have bound its listener yet, and create_connection's
            # timeout does NOT cover ECONNREFUSED, which returns instantly.
            # Only connection-level errors retry; resolution errors raise.
            import time as _time
            deadline = _time.time() + 180.0
            while True:
                if self._closed:
                    raise OSError("bus closed during connect")
                try:
                    sock = socket.create_connection((host, int(port)),
                                                    timeout=60)
                    break
                except (ConnectionRefusedError, ConnectionResetError,
                        ConnectionAbortedError, TimeoutError):
                    if _time.time() >= deadline:
                        raise
                    _time.sleep(0.2)
            with self._mu:
                existing = self._socks.get(owner)
                if existing is None:
                    entry = (sock, threading.Lock())
                    self._socks[owner] = entry
                else:  # lost the race: reuse the winner's socket
                    sock.close()
                    entry = existing
        sock, sock_mu = entry
        with sock_mu:
            _send_msg(sock, (dst, payload))

    def close(self):
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass
        for s, _mu in self._socks.values():
            try:
                s.close()
            except OSError:
                pass


def _host_payload(payload):
    """Device arrays -> numpy before pickling onto the wire (the DCN-hop
    analog: cross-host tensors move through host memory)."""
    try:
        import jax
        import numpy as np

        def conv(x):
            return np.asarray(x) if isinstance(x, jax.Array) else x
        return jax.tree.map(conv, payload)
    except ImportError:
        return payload


class Interceptor:
    """~ interceptor.h:46 — an actor with an inbox loop on its own thread."""

    def __init__(self, task: TaskNode, bus: MessageBus):
        self.task = task
        self.bus = bus
        self.inbox = bus.register(task.task_id)
        self._thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None

    def handle(self, payload):
        raise NotImplementedError

    def _loop(self):
        while True:
            payload = self.inbox.get()
            if payload is _STOP:
                for dst in self.task.downstream:
                    self.bus.send(dst, _STOP)
                break
            try:
                self.handle(payload)
            except BaseException as e:   # propagate to the carrier
                self.error = e
                for dst in self.task.downstream:
                    self.bus.send(dst, _STOP)
                # keep draining the bounded inbox until _STOP arrives, else
                # an upstream blocked in bus.send on this queue never exits
                # and Carrier.run's join() hangs instead of raising
                while True:
                    p = self.inbox.get()
                    if p is _STOP:
                        break
                break

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def join(self):
        if self._thread:
            self._thread.join()


class ComputeInterceptor(Interceptor):
    """~ compute_interceptor.cc: run the stage program, forward the result."""

    def handle(self, payload):
        idx, value = payload
        out = self.task.program(value)
        for dst in self.task.downstream:
            self.bus.send(dst, (idx, out))


class _SinkInterceptor(Interceptor):
    def __init__(self, task, bus, results: dict):
        super().__init__(task, bus)
        self._results = results

    def handle(self, payload):
        idx, value = payload
        self._results[idx] = value


class Carrier:
    """~ carrier.h:49 — owns the interceptors of one runtime graph and
    pushes micro-batches through them."""

    def __init__(self, tasks: List[TaskNode]):
        self.bus = MessageBus()
        self.results: Dict[int, Any] = {}
        self.interceptors: List[Interceptor] = []
        by_id = {t.task_id: t for t in tasks}
        # wire linear order if the graph has no explicit edges
        ordered = sorted(tasks, key=lambda t: t.task_id)
        if not any(t.downstream for t in tasks):
            for a, b in zip(ordered, ordered[1:]):
                a.add_downstream_task(b.task_id)
                b.add_upstream_task(a.task_id)
        sink = TaskNode(rank=-1, node_type="Sink",
                        task_id=max(by_id) + 1 if by_id else 0)
        tails = [t for t in tasks
                 if not t.downstream or all(d == sink.task_id
                                            for d in t.downstream)]
        for t in tails:
            if sink.task_id not in t.downstream:
                t.add_downstream_task(sink.task_id)
        self._head = ordered[0] if ordered else sink
        for t in tasks:
            self.interceptors.append(ComputeInterceptor(t, self.bus))
        self.interceptors.append(
            _SinkInterceptor(sink, self.bus, self.results))
        for ic in self.interceptors:
            ic.start()

    def run(self, microbatches: List[Any]) -> List[Any]:
        self.results.clear()
        for i, mb in enumerate(microbatches):
            self.bus.send(self._head.task_id, (i, mb))
        self.bus.send(self._head.task_id, _STOP)
        for ic in self.interceptors:
            ic.join()
        for ic in self.interceptors:
            if ic.error is not None:
                raise ic.error
        return [self.results[i] for i in sorted(self.results)]


class DistCarrier:
    """Cross-process carrier: each rank owns the interceptors of its local
    TaskNodes; messages between ranks ride the DistMessageBus
    (~ carrier.cc + message_bus.cc in multi-rank deployment).

    Graph convention: tasks are linearly chained by task_id (explicit
    edges honored when present); rank 0 feeds microbatches, the rank
    owning the highest task id hosts the sink and returns the gathered
    results — other ranks return [].
    """

    def __init__(self, tasks: List[TaskNode], rank: int,
                 addrs: Dict[int, str]):
        self.rank = rank
        ordered = sorted(tasks, key=lambda t: t.task_id)
        if not any(t.downstream for t in tasks):
            for a, b in zip(ordered, ordered[1:]):
                a.add_downstream_task(b.task_id)
                b.add_upstream_task(a.task_id)
        sink_owner = ordered[-1].rank
        sink = TaskNode(rank=sink_owner, node_type="Sink",
                        task_id=ordered[-1].task_id + 1)
        # every tail (no downstream) feeds the sink — same tails rule as
        # the local Carrier, so multi-branch graphs don't drop results
        for t in ordered:
            if not t.downstream:
                t.add_downstream_task(sink.task_id)
        all_tasks = ordered + [sink]
        task_to_rank = {t.task_id: t.rank for t in all_tasks}
        self.bus = DistMessageBus(task_to_rank, rank, addrs)
        self._head = ordered[0]
        self.results: Dict[int, Any] = {}
        self.interceptors: List[Interceptor] = []
        for t in ordered:
            if t.rank == rank:
                self.interceptors.append(ComputeInterceptor(t, self.bus))
        if sink_owner == rank:
            self.interceptors.append(
                _SinkInterceptor(sink, self.bus, self.results))
        for ic in self.interceptors:
            ic.start()

    def run(self, microbatches: Optional[List[Any]] = None) -> List[Any]:
        # NO results.clear() here: the sink interceptor starts collecting
        # at construction, and a fast feeder rank can deliver results
        # before the sink rank's main thread even enters run() — clearing
        # now would drop them (the round-3 load-dependent flake). The
        # carrier is one-shot: construct a new one per run.
        if self.rank == self._head.rank:
            for i, mb in enumerate(microbatches or []):
                self.bus.send(self._head.task_id, (i, mb))
            self.bus.send(self._head.task_id, _STOP)
        for ic in self.interceptors:
            ic.join()
        for ic in self.interceptors:
            if ic.error is not None:
                raise ic.error
        return [self.results[i] for i in sorted(self.results)]

    def close(self):
        self.bus.close()


class FleetExecutor:
    """~ fleet_executor.cc: build the runtime graph from stage programs and
    stream micro-batches through the carrier."""

    def __init__(self, stage_programs: List[Callable]):
        self.tasks = [TaskNode(rank=i, program=fn, task_id=i)
                      for i, fn in enumerate(stage_programs)]

    def run(self, microbatches: List[Any]) -> List[Any]:
        carrier = Carrier(list(self.tasks))
        return carrier.run(microbatches)


class DistModelConfig:
    """~ dist_model.h DistModelConfig."""

    def __init__(self, model=None, nranks: int = 1, rank: int = 0,
                 n_microbatches: int = 4):
        self.model = model
        self.nranks = nranks
        self.rank = rank
        self.n_microbatches = n_microbatches


class DistModel:
    """~ dist_model.cc — the distributed inference entry riding the
    fleet-executor runtime: a Layer's sublayers are segmented into
    ``n_stages`` jitted stage programs; micro-batches stream through them
    with overlapped dispatch (hybrid_parallel_inference analog)."""

    def __init__(self, config: DistModelConfig, n_stages: int = 2):
        import jax
        from ..core.tensor import Tensor
        model = config.model
        self._config = config
        subs = [l for l in model.children()]
        if len(subs) < n_stages:
            n_stages = max(1, len(subs))
        per = (len(subs) + n_stages - 1) // n_stages
        segments = [subs[i * per:(i + 1) * per] for i in range(n_stages)]
        segments = [s for s in segments if s]

        def make_stage(layers):
            def stage(x):
                t = Tensor(x)
                from ..autograd import tape as _tape
                with __import__("paddle_tpu").autograd.no_grad():
                    for l in layers:
                        t = l(t)
                return t._value
            return jax.jit(stage)
        self._exe = FleetExecutor([make_stage(s) for s in segments])

    def run(self, inputs) -> list:
        """inputs: full batch (Tensor/array); returns stitched outputs."""
        import jax.numpy as jnp
        import numpy as np
        from ..core.tensor import Tensor
        x = inputs._value if isinstance(inputs, Tensor) else jnp.asarray(inputs)
        n = self._config.n_microbatches
        B = x.shape[0]
        n = min(n, B)
        sizes = [B // n + (1 if i < B % n else 0) for i in range(n)]
        mbs, off = [], 0
        for s in sizes:
            mbs.append(x[off:off + s])
            off += s
        outs = self._exe.run(mbs)
        return Tensor(jnp.concatenate(outs, axis=0))
