"""Distributed program-level passes.

~ python/paddle/distributed/passes/ (pass_base.py PassBase/PassContext +
register_pass, with auto_parallel_{amp,fp16,recompute,sharding,
gradient_merge}.py and fuse_all_reduce.py).

TPU form: the reference's passes rewrite ProgramDesc blocks; here the
train-step factories compile whatever the DistributedStrategy requests, so
a pass is a typed transformation of (strategy, model, optimizer) — amp
flips the bf16 policy, recompute flips remat, sharding sets the ZeRO axis,
gradient_merge sets accumulate steps. ``fuse_all_reduce`` is advisory (XLA
fuses collective chains itself) but validates/records the bucket size.
The PassManager contract (apply in order, check_before/after) matches the
reference so tooling built against it ports over.
"""
from __future__ import annotations

from typing import Dict, List, Optional

PASS_REGISTRY: Dict[str, type] = {}


def register_pass(name: str):
    """~ pass_base.py register_pass decorator."""
    def deco(cls):
        cls.name = name
        PASS_REGISTRY[name] = cls
        return cls
    return deco


def new_pass(name: str, attrs: Optional[dict] = None):
    """~ paddle.distributed.passes.new_pass."""
    if name not in PASS_REGISTRY:
        raise KeyError(f"no distributed pass named {name!r}; "
                       f"have {sorted(PASS_REGISTRY)}")
    p = PASS_REGISTRY[name]()
    p.attrs = dict(attrs or {})
    return p


class PassContext:
    """~ pass_base.py PassContext: carries strategy/model/optimizer through
    the pipeline + a log of applied passes."""

    def __init__(self, strategy=None, model=None, optimizer=None):
        from ..fleet.distributed_strategy import DistributedStrategy
        self.strategy = strategy if strategy is not None \
            else DistributedStrategy()
        self.model = model
        self.optimizer = optimizer
        self.applied: List[str] = []


class PassBase:
    """~ pass_base.py PassBase."""

    name = "base"

    def __init__(self):
        self.attrs: dict = {}

    def check_before(self, context: PassContext) -> bool:
        return True

    def check_after(self, context: PassContext) -> bool:
        return True

    def apply_impl(self, context: PassContext) -> None:
        raise NotImplementedError

    def apply(self, context: PassContext) -> PassContext:
        if not self.check_before(context):
            raise RuntimeError(f"pass {self.name}: precondition failed")
        self.apply_impl(context)
        context.applied.append(self.name)
        if not self.check_after(context):
            raise RuntimeError(f"pass {self.name}: postcondition failed")
        return context


class PassManager:
    """~ pass_base.py PassManager: ordered application."""

    def __init__(self, passes: List[PassBase]):
        self.passes = list(passes)

    def apply(self, context: PassContext) -> PassContext:
        for p in self.passes:
            context = p.apply(context)
        return context


@register_pass("auto_parallel_amp")
class AMPPass(PassBase):
    """bf16 compute policy (~ auto_parallel_amp.py O1)."""

    def apply_impl(self, ctx):
        ctx.strategy.amp = True
        ctx.strategy.amp_configs = {**getattr(ctx.strategy, "amp_configs",
                                              {}) ,
                                    "dtype": self.attrs.get("dtype",
                                                            "bfloat16"),
                                    "level": self.attrs.get("level", "O1")}

    def check_after(self, ctx):
        return bool(ctx.strategy.amp)


@register_pass("auto_parallel_fp16")
class FP16Pass(AMPPass):
    """O2 (pure low-precision params) variant (~ auto_parallel_fp16.py)."""

    def apply_impl(self, ctx):
        self.attrs.setdefault("level", "O2")
        super().apply_impl(ctx)
        if ctx.model is not None and hasattr(ctx.model, "to"):
            ctx.model.to(dtype=self.attrs.get("dtype", "bfloat16"))


@register_pass("auto_parallel_recompute")
class RecomputePass(PassBase):
    """Activation rematerialization (~ auto_parallel_recompute.py) —
    compiled as jax.checkpoint around layer blocks."""

    def apply_impl(self, ctx):
        ctx.strategy.recompute = True
        ctx.strategy.recompute_configs = {
            "checkpoints": self.attrs.get("checkpoints", [])}

    def check_after(self, ctx):
        return bool(ctx.strategy.recompute)


@register_pass("auto_parallel_sharding")
class ShardingPass(PassBase):
    """ZeRO state sharding over the 'sharding' axis
    (~ auto_parallel_sharding.py)."""

    def apply_impl(self, ctx):
        stage = int(self.attrs.get("stage", 1))
        ctx.strategy.sharding = True
        ctx.strategy.sharding_configs = {
            "stage": stage,
            "sharding_degree": self.attrs.get("degree", 8)}
        if ctx.optimizer is not None:
            ctx.optimizer._shard_states_axis = "sharding"
        if stage >= 3 and ctx.model is not None:
            from ..sharding import _annotate_stage3
            _annotate_stage3(ctx.model)

    def check_after(self, ctx):
        return bool(ctx.strategy.sharding)


@register_pass("auto_parallel_gradient_merge")
class GradientMergePass(PassBase):
    """Micro-batch gradient accumulation (~ auto_parallel_gradient_merge)."""

    def apply_impl(self, ctx):
        k = int(self.attrs.get("k_steps", 4))
        ctx.strategy.gradient_merge = True
        ctx.strategy.gradient_merge_configs = {"k_steps": k,
                                               "avg": self.attrs.get("avg",
                                                                     True)}

    def check_after(self, ctx):
        return bool(ctx.strategy.gradient_merge)


@register_pass("fuse_all_reduce")
class FuseAllReducePass(PassBase):
    """Gradient-bucket fusion (~ fuse_all_reduce.py). XLA's collective
    combiner does the fusing at compile time; the pass records the bucket
    budget it should combine up to."""

    def apply_impl(self, ctx):
        mb = int(self.attrs.get("fuse_grad_size_in_MB", 32))
        ctx.strategy.fuse_grad_size_in_MB = mb

    def check_after(self, ctx):
        return ctx.strategy.fuse_grad_size_in_MB > 0
