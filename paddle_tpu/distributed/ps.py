"""Parameter-server capability slot.

The reference's brpc PS stack (paddle/fluid/distributed/ps/: dense/sparse
tables, accessors, geo-SGD — SURVEY.md §2.2) is declared out of the TPU
north-star scope (§7 non-goals); this module provides the minimal
TPU-idiomatic equivalent of its *capability*: a sparse embedding table
served over TCPStore with push/pull + server-side SGD, good for the
embedding-dominated workloads PS mode exists for. In-process mode doubles
as the reference's ps_local_client.h test double.
"""
from __future__ import annotations

import pickle
import threading
from typing import Dict, Optional

import numpy as np

from .store import TCPStore


class SparseTable:
    """Server-side sparse table with SGD update rule
    (~ distributed/ps/table/memory_sparse_table.cc + sparse_sgd_rule.cc)."""

    def __init__(self, dim: int, init_std: float = 0.01, lr: float = 0.01,
                 seed: int = 0):
        self.dim = dim
        self.lr = lr
        self.init_std = init_std
        self._rows: Dict[int, np.ndarray] = {}
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()

    def pull(self, ids: np.ndarray) -> np.ndarray:
        out = np.empty((len(ids), self.dim), np.float32)
        with self._lock:
            for i, key in enumerate(np.asarray(ids).reshape(-1)):
                k = int(key)
                row = self._rows.get(k)
                if row is None:
                    row = (self._rng.standard_normal(self.dim)
                           * self.init_std).astype(np.float32)
                    self._rows[k] = row
                out[i] = row
        return out

    def push(self, ids: np.ndarray, grads: np.ndarray) -> None:
        with self._lock:
            for key, g in zip(np.asarray(ids).reshape(-1), grads):
                k = int(key)
                row = self._rows.get(k)
                if row is not None:
                    row -= self.lr * g.astype(np.float32)

    def save(self, path: str):
        with self._lock:
            with open(path, "wb") as f:
                pickle.dump({"dim": self.dim, "rows": self._rows}, f)

    def load(self, path: str):
        with open(path, "rb") as f:
            d = pickle.load(f)
        with self._lock:
            self.dim = d["dim"]
            self._rows = d["rows"]

    def size(self) -> int:
        return len(self._rows)


class PSClient:
    """Client API (~ brpc_ps_client): local-table mode (in-process) or
    remote over TCPStore serialized blobs (small-scale; the brpc data plane
    is out of scope)."""

    def __init__(self, table: Optional[SparseTable] = None,
                 store: Optional[TCPStore] = None, table_id: int = 0):
        self.table = table
        self.store = store
        self.table_id = table_id

    def pull_sparse(self, ids):
        if self.table is not None:
            return self.table.pull(ids)
        self.store.set(f"__ps_req__/{self.table_id}",
                       pickle.dumps(("pull", np.asarray(ids))))
        return pickle.loads(self.store.wait(f"__ps_resp__/{self.table_id}"))

    def push_sparse(self, ids, grads):
        if self.table is not None:
            self.table.push(ids, np.asarray(grads))
            return
        self.store.set(f"__ps_req__/{self.table_id}",
                       pickle.dumps(("push", np.asarray(ids),
                                     np.asarray(grads))))
