"""Parameter server (async/geo training substrate).

~ paddle/fluid/distributed/ps/: brpc PS services with dense/sparse tables
and pluggable SGD accessors (service/brpc_ps_server.cc,
table/memory_sparse_table.cc, table/sparse_sgd_rule.cc). TPU-native
re-design: the data plane is a threaded length-prefixed TCP RPC server
(the brpc role) hosting numpy tables on the host CPU — PS workloads are
embedding-dominated and host-resident by definition; the TPU enters on
the worker side where pulled rows join the compiled training step. Tables
persist via pickle (Table::Save/Load, table.h) and the in-process mode
doubles as the reference's ps_local_client.h test double.
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading
from typing import Dict, Optional

import numpy as np

from .store import TCPStore  # noqa: F401  (re-export for back-compat)


# ---------------------------------------------------------------------------
# update rules (~ table/sparse_sgd_rule.cc: naive / adagrad accessors)
# ---------------------------------------------------------------------------
class SGDRule:
    """Plain SGD (~ SparseNaiveSGDRule)."""

    def __init__(self, lr=0.01):
        self.lr = lr

    def init_state(self, dim):
        return None

    def update(self, row, grad, state):
        row -= self.lr * grad
        return state


class AdagradRule:
    """Adagrad with accumulated squared grads (~ SparseAdaGradSGDRule)."""

    def __init__(self, lr=0.01, eps=1e-8):
        self.lr = lr
        self.eps = eps

    def init_state(self, dim):
        return np.zeros(dim, np.float32)

    def update(self, row, grad, state):
        state += grad * grad
        row -= self.lr * grad / (np.sqrt(state) + self.eps)
        return state


def make_rule(name: str, lr: float):
    if name in ("sgd", "naive"):
        return SGDRule(lr)
    if name == "adagrad":
        return AdagradRule(lr)
    raise ValueError(f"unknown sgd rule {name!r}")


# ---------------------------------------------------------------------------
# tables
# ---------------------------------------------------------------------------
class SparseTable:
    """Lazily-initialized sparse embedding table with a pluggable update
    rule (~ memory_sparse_table.cc)."""

    def __init__(self, dim: int, init_std: float = 0.01, lr: float = 0.01,
                 seed: int = 0, rule: str = "sgd"):
        self.dim = dim
        self.init_std = init_std
        self.rule = make_rule(rule, lr)
        self.lr = lr  # kept for back-compat with round-1 API
        self._rows: Dict[int, np.ndarray] = {}
        self._states: Dict[int, object] = {}
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()

    def pull(self, ids: np.ndarray) -> np.ndarray:
        out = np.empty((len(np.asarray(ids).reshape(-1)), self.dim),
                       np.float32)
        with self._lock:
            for i, key in enumerate(np.asarray(ids).reshape(-1)):
                k = int(key)
                row = self._rows.get(k)
                if row is None:
                    row = (self._rng.standard_normal(self.dim)
                           * self.init_std).astype(np.float32)
                    self._rows[k] = row
                    self._states[k] = self.rule.init_state(self.dim)
                out[i] = row
        return out

    def push(self, ids: np.ndarray, grads: np.ndarray) -> None:
        grads = np.asarray(grads, np.float32).reshape(-1, self.dim)
        with self._lock:
            for key, g in zip(np.asarray(ids).reshape(-1), grads):
                k = int(key)
                row = self._rows.get(k)
                if row is not None:
                    self._states[k] = self.rule.update(row, g,
                                                       self._states.get(k))

    def save(self, path: str):
        with self._lock:
            with open(path, "wb") as f:
                pickle.dump({"dim": self.dim, "rows": self._rows,
                             "states": self._states}, f)

    def load(self, path: str):
        with open(path, "rb") as f:
            d = pickle.load(f)
        with self._lock:
            self.dim = d["dim"]
            self._rows = d["rows"]
            self._states = d.get("states", {})

    def size(self) -> int:
        return len(self._rows)


class SSDSparseTable(SparseTable):
    """Disk-backed sparse table: hot rows in memory, cold rows on disk
    (~ table/ssd_sparse_table.cc, whose rocksdb store here is sqlite —
    in the Python stdlib, transactional, and fine for the host-side
    embedding workload). An LRU at ``mem_rows`` evicts (row, rule-state)
    pairs to disk; pulls fault them back in. The update rule only ever
    runs on in-memory rows — push targets were just pulled.
    """

    def __init__(self, dim: int, path: str, mem_rows: int = 100_000,
                 **kw):
        super().__init__(dim, **kw)
        import sqlite3
        self.mem_rows = max(1, mem_rows)
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS rows ("
            "key INTEGER PRIMARY KEY, row BLOB, state BLOB)")
        self._db.commit()

    # -- disk I/O (all callers hold self._lock) ---------------------------
    def _disk_get(self, k: int):
        cur = self._db.execute(
            "SELECT row, state FROM rows WHERE key=?", (k,))
        hit = cur.fetchone()
        if hit is None:
            return None
        row = np.frombuffer(hit[0], np.float32).copy()
        state = pickle.loads(hit[1]) if hit[1] is not None else None
        return row, state

    def _disk_put(self, k: int, row, state):
        self._db.execute(
            "INSERT OR REPLACE INTO rows (key, row, state) VALUES (?,?,?)",
            (k, np.asarray(row, np.float32).tobytes(),
             None if state is None else pickle.dumps(state)))

    def _evict(self):
        while len(self._rows) > self.mem_rows:
            k, row = next(iter(self._rows.items()))  # LRU head
            self._disk_put(k, row, self._states.get(k))
            del self._rows[k]
            self._states.pop(k, None)
        self._db.commit()

    def _touch(self, k: int):
        # dict preserves insertion order; re-inserting marks recency
        row = self._rows.pop(k)
        st = self._states.pop(k, None)
        self._rows[k] = row
        self._states[k] = st

    def pull(self, ids: np.ndarray) -> np.ndarray:
        flat = np.asarray(ids).reshape(-1)
        out = np.empty((len(flat), self.dim), np.float32)
        with self._lock:
            for i, key in enumerate(flat):
                k = int(key)
                row = self._rows.get(k)
                if row is None:
                    hit = self._disk_get(k)
                    if hit is not None:
                        row, st = hit
                        self._rows[k] = row
                        self._states[k] = st
                    else:
                        row = (self._rng.standard_normal(self.dim)
                               * self.init_std).astype(np.float32)
                        self._rows[k] = row
                        self._states[k] = self.rule.init_state(self.dim)
                else:
                    self._touch(k)
                out[i] = row
            self._evict()
        return out

    def push(self, ids: np.ndarray, grads: np.ndarray) -> None:
        grads = np.asarray(grads, np.float32).reshape(-1, self.dim)
        with self._lock:
            for key, g in zip(np.asarray(ids).reshape(-1), grads):
                k = int(key)
                row = self._rows.get(k)
                if row is None:
                    # evicted between pull and push (another trainer's
                    # pull crowded it out): fault it back in
                    hit = self._disk_get(k)
                    if hit is None:
                        continue
                    row, st = hit
                    self._rows[k] = row
                    self._states[k] = st
                self._states[k] = self.rule.update(row, g,
                                                   self._states.get(k))
            self._evict()

    def size(self) -> int:
        with self._lock:
            # union: a row may exist both in memory (hot) and on disk
            # (stale evicted copy)
            disk_keys = {k for (k,) in
                         self._db.execute("SELECT key FROM rows")}
            return len(disk_keys | set(self._rows))

    def load(self, path: str):
        """Replace ALL state with the snapshot: without clearing the
        disk store, stale pre-load rows would resurrect on pull and
        inflate size()."""
        with open(path, "rb") as f:
            d = pickle.load(f)
        with self._lock:
            self._db.execute("DELETE FROM rows")
            self.dim = d["dim"]
            self._rows = dict(d["rows"])
            self._states = dict(d.get("states", {}))
            self._evict()

    def save(self, path: str):
        with self._lock:
            for k, row in self._rows.items():
                self._disk_put(k, row, self._states.get(k))
            self._db.commit()
            rows, states = {}, {}
            for k, rb, sb in self._db.execute(
                    "SELECT key, row, state FROM rows"):
                rows[k] = np.frombuffer(rb, np.float32).copy()
                states[k] = pickle.loads(sb) if sb is not None else None
            with open(path, "wb") as f:
                pickle.dump({"dim": self.dim, "rows": rows,
                             "states": states}, f)


class DenseTable:
    """Dense parameter region (~ table/common_dense_table.cc): one flat
    float32 vector, push applies the update rule."""

    def __init__(self, size: int, lr: float = 0.01, rule: str = "sgd",
                 init: Optional[np.ndarray] = None):
        self.data = (np.zeros(size, np.float32) if init is None
                     else np.asarray(init, np.float32).copy())
        self.rule = make_rule(rule, lr)
        self._state = self.rule.init_state(size)
        self._lock = threading.Lock()

    def pull(self) -> np.ndarray:
        with self._lock:
            return self.data.copy()

    def push(self, grad: np.ndarray) -> None:
        with self._lock:
            self._state = self.rule.update(
                self.data, np.asarray(grad, np.float32), self._state)

    def set(self, values: np.ndarray) -> None:
        with self._lock:
            self.data[:] = np.asarray(values, np.float32)


# ---------------------------------------------------------------------------
# RPC plumbing (length-prefixed pickle frames — the brpc role)
# ---------------------------------------------------------------------------
def _send_msg(sock: socket.socket, obj) -> None:
    blob = pickle.dumps(obj, protocol=4)
    sock.sendall(struct.pack("<q", len(blob)) + blob)


def _recv_msg(sock: socket.socket):
    hdr = _recv_exact(sock, 8)
    if hdr is None:
        return None
    (n,) = struct.unpack("<q", hdr)
    blob = _recv_exact(sock, n)
    return None if blob is None else pickle.loads(blob)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class PSServer:
    """Threaded PS RPC server hosting tables (~ brpc_ps_server.cc
    PsService: one handler thread per connected worker)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._tables: Dict[int, object] = {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._threads = []
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def add_sparse_table(self, table_id: int, dim: int, **kw) -> SparseTable:
        t = SparseTable(dim, **kw)
        self._tables[table_id] = t
        return t

    def add_ssd_sparse_table(self, table_id: int, dim: int, path: str,
                             mem_rows: int = 100_000,
                             **kw) -> "SSDSparseTable":
        """Disk-backed table (~ ssd_sparse_table.cc) — embedding vocabs
        larger than host memory spill to ``path``."""
        t = SSDSparseTable(dim, path, mem_rows=mem_rows, **kw)
        self._tables[table_id] = t
        return t

    def add_dense_table(self, table_id: int, size: int, **kw) -> DenseTable:
        t = DenseTable(size, **kw)
        self._tables[table_id] = t
        return t

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            th = threading.Thread(target=self._serve, args=(conn,),
                                  daemon=True)
            th.start()
            self._threads.append(th)

    def _serve(self, conn: socket.socket):
        with conn:
            while True:
                msg = _recv_msg(conn)
                if msg is None:
                    return
                op, table_id, payload = msg
                try:
                    resp = self._dispatch(op, table_id, payload)
                except Exception as e:  # noqa: BLE001 — error goes to client
                    resp = ("err", repr(e))
                _send_msg(conn, resp)

    def _dispatch(self, op, table_id, payload):
        t = self._tables.get(table_id)
        if t is None and op not in ("stop",):
            return ("err", f"no table {table_id}")
        if op == "pull_sparse":
            return ("ok", t.pull(payload))
        if op == "push_sparse":
            t.push(*payload)
            return ("ok", None)
        if op == "pull_dense":
            return ("ok", t.pull())
        if op == "push_dense":
            t.push(payload)
            return ("ok", None)
        if op == "set_dense":
            t.set(payload)
            return ("ok", None)
        if op == "save":
            t.save(payload)
            return ("ok", None)
        if op == "load":
            t.load(payload)
            return ("ok", None)
        if op == "size":
            return ("ok", t.size())
        if op == "stop":
            self._stop.set()
            return ("ok", None)
        return ("err", f"unknown op {op}")

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


class PSClient:
    """Worker-side client (~ brpc_ps_client.h).

    Modes: in-process local table (ps_local_client.h double), or remote
    over the PSServer RPC. `async_push` gives geo-SGD-style non-blocking
    gradient push (the reference's geo mode batches pushes off the
    critical path)."""

    def __init__(self, table: Optional[SparseTable] = None,
                 store=None, table_id: int = 0,
                 server_addr: Optional[str] = None):
        self.table = table
        self.table_id = table_id
        self._sock = None
        self._mu = threading.Lock()
        self._async_q = []
        self._async_cv = threading.Condition()
        self._async_inflight = 0
        self._async_thread = None
        if server_addr is not None:
            host, port = server_addr.rsplit(":", 1)
            self._sock = socket.create_connection((host, int(port)),
                                                  timeout=60)

    # -- rpc -------------------------------------------------------------
    def _call(self, op, payload, table_id=None):
        with self._mu:
            _send_msg(self._sock,
                      (op, self.table_id if table_id is None else table_id,
                       payload))
            resp = _recv_msg(self._sock)
        if resp is None:
            raise ConnectionError("PS server closed connection")
        status, value = resp
        if status != "ok":
            raise RuntimeError(f"PS error: {value}")
        return value

    # -- sparse ----------------------------------------------------------
    def pull_sparse(self, ids, table_id=None):
        if self.table is not None:
            return self.table.pull(ids)
        return self._call("pull_sparse", np.asarray(ids), table_id)

    def push_sparse(self, ids, grads, table_id=None):
        if self.table is not None:
            self.table.push(ids, np.asarray(grads))
            return
        self._call("push_sparse",
                   (np.asarray(ids), np.asarray(grads)), table_id)

    # -- dense -----------------------------------------------------------
    def pull_dense(self, table_id=None):
        return self._call("pull_dense", None, table_id)

    def push_dense(self, grad, table_id=None):
        self._call("push_dense", np.asarray(grad), table_id)

    def set_dense(self, values, table_id=None):
        self._call("set_dense", np.asarray(values), table_id)

    # -- async (geo) push -------------------------------------------------
    def async_push_sparse(self, ids, grads, table_id=None):
        if self._async_thread is None:
            self._async_thread = threading.Thread(target=self._async_loop,
                                                  daemon=True)
            self._async_thread.start()
        with self._async_cv:
            self._async_q.append((np.asarray(ids).copy(),
                                  np.asarray(grads).copy(), table_id))
            self._async_cv.notify_all()

    def _async_loop(self):
        while True:
            with self._async_cv:
                while not self._async_q:
                    self._async_cv.wait()
                ids, grads, table_id = self._async_q.pop(0)
                if ids is None:
                    return
                self._async_inflight += 1
            try:
                self.push_sparse(ids, grads, table_id)
            finally:
                with self._async_cv:
                    self._async_inflight -= 1
                    self._async_cv.notify_all()

    def flush(self):
        """Barrier for async pushes (geo-SGD step boundary): returns only
        after every enqueued push has been applied server-side."""
        with self._async_cv:
            while self._async_q or self._async_inflight:
                self._async_cv.wait(timeout=0.1)

    # -- persistence / admin ---------------------------------------------
    def save(self, path, table_id=None):
        self._call("save", path, table_id)

    def load(self, path, table_id=None):
        self._call("load", path, table_id)

    def table_size(self, table_id=None):
        return self._call("size", None, table_id)

    def close(self):
        if self._async_thread is not None:
            with self._async_cv:
                self._async_q.append((None, None, None))
                self._async_cv.notify_all()
            self._async_thread.join(timeout=5)
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
