"""TCPStore: rendezvous key-value store.

~ paddle/fluid/distributed/store/tcp_store.h:91 (core.TCPStore, used by
init_parallel_env for id exchange + barrier). Native C++ implementation in
csrc/tcp_store.cc bound via ctypes; pure-python socket fallback keeps the
exact wire protocol so mixed deployments interoperate.
"""
from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Optional

from ..utils import native as _native


class _PyClient:
    """Pure-python client speaking the csrc/tcp_store.cc protocol."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        deadline = time.time() + timeout
        last = None
        while time.time() < deadline:
            try:
                self.sock = socket.create_connection((host, port), timeout=5)
                self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self.lock = threading.Lock()
                return
            except OSError as e:
                last = e
                time.sleep(0.2)
        raise ConnectionError(f"cannot reach TCPStore {host}:{port}: {last}")

    def _roundtrip(self, op: int, key: bytes, value: bytes) -> bytes:
        with self.lock:
            msg = (struct.pack("<BI", op, len(key)) + key
                   + struct.pack("<I", len(value)) + value)
            self.sock.sendall(msg)
            rlen = struct.unpack("<I", self._recv(4))[0]
            return self._recv(rlen) if rlen else b""

    def _recv(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("TCPStore connection closed")
            buf += chunk
        return buf

    def close(self):
        self.sock.close()


class _PyServer:
    """Pure-python server (same protocol)."""

    def __init__(self, port: int):
        self.data = {}
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("0.0.0.0", port))
        self.sock.listen(128)
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        def recv(n):
            buf = b""
            while len(buf) < n:
                c = conn.recv(n - len(buf))
                if not c:
                    raise ConnectionError
                buf += c
            return buf
        try:
            while True:
                op, klen = struct.unpack("<BI", recv(5))
                key = recv(klen).decode()
                vlen = struct.unpack("<I", recv(4))[0]
                value = recv(vlen)
                if op == 0:
                    with self.cond:
                        self.data[key] = value
                        self.cond.notify_all()
                    out = b""
                elif op == 1:
                    with self.lock:
                        out = self.data.get(key, b"")
                elif op == 2:
                    delta = struct.unpack("<q", value)[0] if vlen == 8 else 0
                    with self.cond:
                        cur = struct.unpack(
                            "<q", self.data.get(key, b"\0" * 8))[0]
                        new = cur + delta
                        self.data[key] = struct.pack("<q", new)
                        self.cond.notify_all()
                    out = struct.pack("<q", new)
                elif op == 3:
                    with self.cond:
                        while key not in self.data:
                            self.cond.wait()
                        out = self.data[key]
                elif op == 4:
                    with self.cond:
                        self.data.pop(key, None)
                    out = b""
                else:
                    return
                conn.sendall(struct.pack("<I", len(out)) + out)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def stop(self):
        self.sock.close()


class TCPStore:
    """~ core.TCPStore(host, port, is_master, world_size, timeout)."""

    def __init__(self, host: str, port: int, is_master: bool = False,
                 world_size: int = 1, timeout: float = 300.0):
        self.host = host
        self.port = port
        self.is_master = is_master
        self._lib = _native.get_lib()
        self._server = None
        self._fd = None
        self._py = None
        # one client socket per store: concurrent threads interleaving
        # request/response frames on the same fd deadlock the protocol
        # (observed in the elastic heartbeat thread vs the caller)
        self._mu = threading.Lock()
        if is_master:
            if self._lib is not None:
                self._server = self._lib.tcpstore_server_start(port)
                if not self._server:
                    raise RuntimeError(f"cannot bind TCPStore on :{port}")
            else:
                self._server = _PyServer(port)
        # resolve hostname for the C client (needs dotted quad)
        ip = socket.gethostbyname(host)
        if self._lib is not None:
            deadline = time.time() + timeout
            fd = -1
            while time.time() < deadline:
                fd = self._lib.tcpstore_connect(ip.encode(), port)
                if fd >= 0:
                    break
                time.sleep(0.2)
            if fd < 0:
                raise ConnectionError(f"cannot reach TCPStore {host}:{port}")
            self._fd = fd
        else:
            self._py = _PyClient(ip, port, timeout)

    # ---- API (paddle parity: set/get/wait/add) ----------------------------
    def set(self, key: str, value) -> None:
        v = value if isinstance(value, bytes) else str(value).encode()
        if self._fd is not None:
            with self._mu:
                rc = self._lib.tcpstore_set(self._fd, key.encode(), v, len(v))
            if rc != 0:
                raise ConnectionError("TCPStore set failed")
        else:
            self._py._roundtrip(0, key.encode(), v)

    def get(self, key: str) -> bytes:
        if self._fd is not None:
            buf = (ctypes_buffer := bytearray(1 << 20))
            import ctypes
            c_buf = (ctypes.c_char * len(buf)).from_buffer(buf)
            with self._mu:
                n = self._lib.tcpstore_get(self._fd, key.encode(), c_buf,
                                           len(buf))
            if n < 0:
                raise ConnectionError("TCPStore get failed")
            return bytes(buf[:n])
        return self._py._roundtrip(1, key.encode(), b"")

    def add(self, key: str, delta: int) -> int:
        if self._fd is not None:
            with self._mu:
                out = self._lib.tcpstore_add(self._fd, key.encode(), delta)
            if out == -(2 ** 63):
                raise ConnectionError("TCPStore add failed")
            return int(out)
        import struct as _s
        out = self._py._roundtrip(2, key.encode(), _s.pack("<q", delta))
        return _s.unpack("<q", out)[0]

    def wait(self, key: str) -> bytes:
        if self._fd is not None:
            import ctypes
            buf = bytearray(1 << 20)
            c_buf = (ctypes.c_char * len(buf)).from_buffer(buf)
            with self._mu:
                n = self._lib.tcpstore_wait(self._fd, key.encode(), c_buf,
                                            len(buf))
            if n < 0:
                raise ConnectionError("TCPStore wait failed")
            return bytes(buf[:n])
        return self._py._roundtrip(3, key.encode(), b"")

    def delete_key(self, key: str) -> None:
        if self._fd is not None:
            with self._mu:
                self._lib.tcpstore_delete(self._fd, key.encode())
        else:
            self._py._roundtrip(4, key.encode(), b"")

    def barrier(self, name: str, world_size: int, timeout: float = 300.0):
        """all ranks add 1, wait for count==world_size."""
        count = self.add(f"__barrier__/{name}", 1)
        if count == world_size:
            self.set(f"__barrier_done__/{name}", b"1")
        self.wait(f"__barrier_done__/{name}")

    def close(self):
        if self._fd is not None:
            self._lib.tcpstore_close(self._fd)
            self._fd = None
        if self._py is not None:
            self._py.close()
