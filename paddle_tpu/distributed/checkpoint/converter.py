"""Checkpoint re-shard converter: move tensors between parallel strategies.

~ python/paddle/distributed/auto_parallel/converter.py (Converter: merge
rank-shards saved under one (process_mesh, dims_mapping) layout into the
complete tensor, then re-slice for the current layout; prefix-match
fallback for renamed params) — SURVEY.md §5 flags this as the load-bearing
checkpoint capability.

Layout description (dist_attr), matching the reference's:
  {"process_shape": [pm0, pm1, ...],      # mesh shape
   "process_group": [global rank ids],    # row-major over process_shape
   "dims_mapping":  [m_or_-1 per dim]}    # tensor dim d is split over mesh
                                          # dim dims_mapping[d]; -1 = whole

TPU bridge: ``dist_attr_from_sharding`` derives a dist_attr from a
``jax.sharding.NamedSharding`` so shards written from a Mesh-sharded train
state can be converted offline to any other topology.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np


def _coords(rank_pos: int, process_shape: Sequence[int]) -> List[int]:
    """Row-major mesh coordinates of the rank at position ``rank_pos`` in
    the process group."""
    out = []
    rem = rank_pos
    for extent in reversed(process_shape):
        out.append(rem % extent)
        rem //= extent
    return out[::-1]


def _shard_slices(global_shape, dims_mapping, process_shape, coords):
    """Slice objects selecting one rank's shard of the complete tensor."""
    slices = []
    for d, size in enumerate(global_shape):
        m = dims_mapping[d]
        if m is None or m == -1:
            slices.append(slice(0, size))
        else:
            parts = process_shape[m]
            if size % parts != 0:
                raise ValueError(
                    f"dim {d} of size {size} not divisible by mesh dim "
                    f"{m} of extent {parts}")
            step = size // parts
            c = coords[m]
            slices.append(slice(c * step, (c + 1) * step))
    return tuple(slices)


def merge_with_dist_attr(tensor_list: List[np.ndarray], dist_attr) -> np.ndarray:
    """Assemble the complete tensor from every rank's shard
    (~ Converter.merge_with_dist_attr)."""
    process_shape = list(dist_attr["process_shape"])
    group = list(dist_attr["process_group"])
    dims_mapping = list(dist_attr["dims_mapping"])
    if len(tensor_list) != len(group):
        raise ValueError(
            f"got {len(tensor_list)} shards for a process group of "
            f"{len(group)}")
    shard0 = np.asarray(tensor_list[0])
    global_shape = []
    for d, size in enumerate(shard0.shape):
        m = dims_mapping[d]
        global_shape.append(size if m in (None, -1)
                            else size * process_shape[m])
    out = np.empty(global_shape, dtype=shard0.dtype)
    for pos in range(len(group)):
        coords = _coords(pos, process_shape)
        sl = _shard_slices(global_shape, dims_mapping, process_shape, coords)
        out[sl] = np.asarray(tensor_list[pos])
    return out


def slice_with_dist_attr(tensor: np.ndarray, dist_attr) -> List[np.ndarray]:
    """Split the complete tensor into one shard per rank of the group
    (~ Converter.slice_with_dist_attr)."""
    process_shape = list(dist_attr["process_shape"])
    group = list(dist_attr["process_group"])
    dims_mapping = list(dist_attr["dims_mapping"])
    tensor = np.asarray(tensor)
    shards = []
    for pos in range(len(group)):
        coords = _coords(pos, process_shape)
        sl = _shard_slices(tensor.shape, dims_mapping, process_shape, coords)
        shards.append(np.ascontiguousarray(tensor[sl]))
    return shards


def _attrs_equal(a, b) -> bool:
    return (list(a["process_shape"]) == list(b["process_shape"])
            and list(a["process_group"]) == list(b["process_group"])
            and [(-1 if m is None else m) for m in a["dims_mapping"]]
            == [(-1 if m is None else m) for m in b["dims_mapping"]])


class Converter:
    """Convert a whole checkpoint between parallel strategies.

    tensors_dict: name -> list of per-rank numpy shards (pre layout order)
    pre_strategy / cur_strategy: name -> dist_attr
    convert() -> name -> list of per-rank shards in the cur layout.
    """

    def __init__(self, tensors_dict: Dict[str, list], pre_strategy,
                 cur_strategy):
        if not tensors_dict:
            raise ValueError("tensors_dict must not be empty")
        if not pre_strategy or not cur_strategy:
            raise ValueError("both strategies must be provided")
        self._tensors_dict = tensors_dict
        self._pre_strategy = pre_strategy
        self._cur_strategy = cur_strategy

    def convert(self, strict: bool = True):
        out = {}
        missing_pre = []
        missing_cur = []
        for name, attr in self._cur_strategy.items():
            if name not in self._tensors_dict or \
                    name not in self._pre_strategy:
                missing_cur.append(name)
                continue
            out[name] = self.merge_and_slice(
                self._tensors_dict[name], self._pre_strategy[name], attr)
        for name in self._tensors_dict:
            if name not in self._cur_strategy:
                missing_pre.append(name)
        if missing_cur:
            if strict:
                raise ValueError(
                    f"tensors missing from the checkpoint: {missing_cur}")
            matched, still_missing = self._prefix_match(missing_cur)
            out.update(matched)
            if still_missing:
                raise ValueError(
                    f"tensors not found even by prefix match: "
                    f"{still_missing}")
        return out

    def _prefix_match(self, names):
        """~ Converter.convert_with_prefix_match: tolerate renamed params
        that share a prefix (e.g. structural renames between runs)."""
        matched = {}
        missing = []
        for name in names:
            best = None
            for cand in self._tensors_dict:
                if cand in self._pre_strategy and (
                        name.startswith(cand) or cand.startswith(name)):
                    if best is None or len(cand) > len(best):
                        best = cand
            if best is None:
                missing.append(name)
            else:
                matched[name] = self.merge_and_slice(
                    self._tensors_dict[best], self._pre_strategy[best],
                    self._cur_strategy[name])
        return matched, missing

    @staticmethod
    def merge_and_slice(tensor_list, pre_dist_attr, cur_dist_attr):
        if _attrs_equal(pre_dist_attr, cur_dist_attr):
            return [np.asarray(t) for t in tensor_list]
        complete = merge_with_dist_attr(tensor_list, pre_dist_attr)
        return slice_with_dist_attr(complete, cur_dist_attr)


# ---- jax sharding bridge ---------------------------------------------------

def dist_attr_from_sharding(sharding, global_shape) -> dict:
    """dist_attr for a jax.sharding.NamedSharding — so shards saved from a
    Mesh-sharded array can be converted to any other topology offline."""
    mesh = sharding.mesh
    axis_names = list(mesh.axis_names)
    process_shape = [mesh.shape[a] for a in axis_names]
    spec = list(sharding.spec) + [None] * (
        len(global_shape) - len(list(sharding.spec)))
    dims_mapping = []
    for entry in spec:
        if entry is None:
            dims_mapping.append(-1)
        elif isinstance(entry, (tuple, list)):
            if len(entry) != 1:
                raise NotImplementedError(
                    "multi-axis sharding of one dim needs a flattened mesh "
                    "axis; reshape the mesh first")
            dims_mapping.append(axis_names.index(entry[0]))
        else:
            dims_mapping.append(axis_names.index(entry))
    n = int(np.prod(process_shape))
    return {"process_shape": process_shape,
            "process_group": list(range(n)),
            "dims_mapping": dims_mapping}


def shards_from_array(arr, sharding=None) -> list:
    """Per-rank shard list (mesh row-major order) of a (possibly sharded)
    jax array — the save-side counterpart of merge_with_dist_attr."""
    import jax
    if sharding is None:
        sharding = getattr(arr, "sharding", None)
    if sharding is None or not hasattr(sharding, "mesh"):
        return [np.asarray(arr)]
    attr = dist_attr_from_sharding(sharding, arr.shape)
    full = np.asarray(arr)
    return slice_with_dist_attr(full, attr)


def save_distributed_checkpoint(state_dict, path, dist_attrs=None):
    """Write a converter-format checkpoint: per-tensor shard lists + attrs.

    ~ auto_parallel dist_saver.save_distributed_checkpoint. For jax-sharded
    arrays the dist_attr is derived automatically."""
    import pickle
    from ...core.tensor import Tensor
    blobs = {}
    attrs = {}
    for name, v in state_dict.items():
        arr = v._value if isinstance(v, Tensor) else v
        sh = getattr(arr, "sharding", None)
        if dist_attrs and name in dist_attrs:
            attr = dist_attrs[name]
            blobs[name] = slice_with_dist_attr(np.asarray(arr), attr)
        elif sh is not None and hasattr(sh, "mesh"):
            attr = dist_attr_from_sharding(sh, arr.shape)
            blobs[name] = shards_from_array(arr, sh)
        else:
            attr = {"process_shape": [1], "process_group": [0],
                    "dims_mapping": [-1] * np.asarray(arr).ndim}
            blobs[name] = [np.asarray(arr)]
        attrs[name] = attr
    with open(path, "wb") as f:
        pickle.dump({"tensors": blobs, "attrs": attrs}, f, protocol=4)


def load_distributed_checkpoint(path, cur_dist_attrs=None, strict=True):
    """Load a converter-format checkpoint, re-sharding to cur_dist_attrs
    when given (else returning merged complete tensors)."""
    import pickle
    with open(path, "rb") as f:
        payload = pickle.load(f)
    tensors, attrs = payload["tensors"], payload["attrs"]
    if cur_dist_attrs is None:
        return {name: merge_with_dist_attr(shards, attrs[name])
                for name, shards in tensors.items()}
    conv = Converter(tensors, attrs, cur_dist_attrs)
    return conv.convert(strict=strict)
