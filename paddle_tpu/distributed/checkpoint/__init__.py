"""Distributed sharded checkpointing.

~ the reference's distributed save/load surface: rank-local state dicts
(PipelineLayer.save_state_dict pp_layers.py:413), auto_parallel dist_saver
+ converter.py (re-shard checkpoints across mesh changes), auto-checkpoint
(fluid/incubate/checkpoint/auto_checkpoint.py:71).

TPU-native backing: orbax (tensorstore) async sharded checkpoint — each
host writes its shards; restore re-shards to the *current* mesh/sharding,
which is the converter.py capability built into the format.
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

from ...core.tensor import Parameter, Tensor


def _to_arrays(state: dict) -> dict:
    return {k: (v._value if isinstance(v, Tensor) else v)
            for k, v in state.items()}


class AsyncCheckpointer:
    """Async sharded checkpointer (auto_checkpoint analog: save every epoch,
    resume by range)."""

    def __init__(self, directory: str):
        import orbax.checkpoint as ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(create=True,
                                                 max_to_keep=3))

    def save(self, step: int, state: dict, wait: bool = False):
        import orbax.checkpoint as ocp
        self._mgr.save(step, args=ocp.args.StandardSave(_to_arrays(state)))
        if wait:
            self._mgr.wait_until_finished()

    def restore(self, step: Optional[int] = None, like: Optional[dict] = None):
        import orbax.checkpoint as ocp
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            return None
        if like is not None:
            import jax.tree_util as jtu
            template = jax.tree.map(
                lambda v: jax.ShapeDtypeStruct(
                    tuple(v.shape), v.dtype,
                    sharding=getattr(v, "sharding", None))
                if hasattr(v, "shape") else v,
                _to_arrays(like))
            out = self._mgr.restore(
                step, args=ocp.args.StandardRestore(template))
        else:
            out = self._mgr.restore(step)
        return out

    def latest_step(self):
        return self._mgr.latest_step()

    def wait(self):
        self._mgr.wait_until_finished()


def save_state_dict(state_dict: dict, path: str, wait: bool = True):
    """Sharded save of a (possibly pjit-sharded) state dict."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, _to_arrays(state_dict), force=True)
    if wait:
        ckptr.wait_until_finished()


def load_state_dict(path: str, template: Optional[dict] = None) -> dict:
    """Restore; if ``template`` (tensors w/ target shardings) is given, the
    arrays are re-sharded to it — mesh-change-safe (converter.py analog)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if template is not None:
        tmpl = jax.tree.map(
            lambda v: jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
            if hasattr(v, "shape") else v, _to_arrays(template))
        return ckptr.restore(path, tmpl)
    return ckptr.restore(path)


from .converter import (  # noqa: F401,E402
    Converter, dist_attr_from_sharding, load_distributed_checkpoint,
    merge_with_dist_attr, save_distributed_checkpoint, shards_from_array,
    slice_with_dist_attr,
)
