"""Eager collective API.

~ python/paddle/distributed/collective.py (all_reduce:592, broadcast:506,
all_gather:814, scatter:914, alltoall:1738, send/recv, barrier:277) and the
ProcessGroup stack it sits on (distributed/collective/ProcessGroup.h:53).

TPU-native design: there are no comm streams or reducers. Two regimes:
  * multi-process (a real pod/slice): host-level collectives via
    jax.experimental.multihost_utils (rendezvous through the coordinator).
    These are the *eager* semantics for script-level sync — the perf path is
    always compiled psum/all_gather inside pjit programs.
  * single process: groups degenerate to identity (world_size 1) — matching
    the reference where collectives on a 1-rank group are no-ops.

ReduceOp / group objects keep the reference API surface.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from . import env as _env
from .topology import ParallelGroup


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Task:
    """Async collective handle ~ ProcessGroup.h:82-146 Task virtuals
    (is_completed/wait/synchronize). JAX dispatch is asynchronous by
    construction, so the 'task' is a view over the result buffers:
    is_completed() polls buffer readiness, wait() blocks until the
    collective's outputs are materialized."""

    def __init__(self, tensors):
        if not isinstance(tensors, (list, tuple)):
            tensors = [tensors]
        self._tensors = list(tensors)
        self._sync_thread = None
        self._sync_done = None
        self._sync_exc = []

    def is_completed(self) -> bool:
        from ..core.sync import is_ready
        return all(is_ready(getattr(t, "_value", t)) for t in self._tensors)

    def wait(self, timeout=None) -> bool:
        """Block until the collective's outputs are materialized. With a
        timeout (seconds), returns False on expiry — ~ ProcessGroup
        Task::Wait(timeout). The bounded wait runs the sync in ONE helper
        thread per Task, reused across retries (readiness polling alone is
        unreliable on platforms whose buffers lack is_ready), so the
        deadline holds on every backend; sync failures re-raise here."""
        from ..core.sync import hard_sync

        def _sync_all():
            for t in self._tensors:
                hard_sync(getattr(t, "_value", t))

        if timeout is None:
            if self._sync_thread is None:
                _sync_all()
                return True
            self._sync_done.wait()
            if self._sync_exc:
                raise self._sync_exc[0]
            return True

        import threading
        if self._sync_thread is None:
            self._sync_done = threading.Event()

            def _worker():
                try:
                    _sync_all()
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    self._sync_exc.append(e)
                finally:
                    self._sync_done.set()

            self._sync_thread = threading.Thread(target=_worker, daemon=True)
            self._sync_thread.start()
        ok = self._sync_done.wait(timeout)
        if ok and self._sync_exc:
            raise self._sync_exc[0]
        return ok

    def synchronize(self) -> None:
        self.wait()


def _maybe_task(tensor, sync_op: bool):
    """sync_op=False returns an awaitable Task (reference async PG path);
    sync_op=True keeps the historical return-the-tensor behavior."""
    return tensor if sync_op else Task(tensor)


_groups = {}
_group_counter = 0


def _default_group() -> ParallelGroup:
    if 0 not in _groups:
        world = _env.get_world_size()
        _groups[0] = ParallelGroup(list(range(world)), _env.get_rank(),
                                   "data", 0)
    return _groups[0]


def new_group(ranks=None, backend=None, timeout=None) -> ParallelGroup:
    """~ collective.py new_group:325."""
    global _group_counter
    _group_counter += 1
    if ranks is None:
        ranks = list(range(_env.get_world_size()))
    g = ParallelGroup(list(ranks), _env.get_rank(), "custom", _group_counter)
    _groups[_group_counter] = g
    return g


def get_group(gid: int = 0) -> Optional[ParallelGroup]:
    return _groups.get(gid)


def is_initialized() -> bool:
    return _env.is_initialized()


def _multi_process() -> bool:
    return jax.process_count() > 1


def _allgather_host(arr):
    from jax.experimental import multihost_utils
    return multihost_utils.process_allgather(arr, tiled=False)


def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """~ collective.py all_reduce:592 — in-place on the Tensor."""
    group = group or _default_group()
    if group.nranks <= 1 or not _multi_process():
        if op == ReduceOp.AVG:
            pass
        return _maybe_task(tensor, sync_op)
    gathered = _allgather_host(tensor._value)  # (world, ...)
    sub = gathered[np.asarray(group.ranks)]
    if op == ReduceOp.SUM:
        out = jnp.sum(sub, axis=0)
    elif op == ReduceOp.MAX:
        out = jnp.max(sub, axis=0)
    elif op == ReduceOp.MIN:
        out = jnp.min(sub, axis=0)
    elif op == ReduceOp.PROD:
        out = jnp.prod(sub, axis=0)
    else:
        out = jnp.mean(sub, axis=0)
    tensor._value = out.astype(tensor._value.dtype)
    return _maybe_task(tensor, sync_op)


def broadcast(tensor: Tensor, src: int, group=None, sync_op=True):
    group = group or _default_group()
    if group.nranks <= 1 or not _multi_process():
        return _maybe_task(tensor, sync_op)
    gathered = _allgather_host(tensor._value)
    tensor._value = jnp.asarray(gathered[src])
    return _maybe_task(tensor, sync_op)


def all_gather(tensor_list: List, tensor: Tensor, group=None, sync_op=True):
    """~ collective.py all_gather:814."""
    group = group or _default_group()
    if group.nranks <= 1 or not _multi_process():
        tensor_list.extend([Tensor(tensor._value)
                            for _ in range(max(group.nranks, 1))])
        return _maybe_task(tensor_list, sync_op)
    gathered = _allgather_host(tensor._value)
    for r in group.ranks:
        tensor_list.append(Tensor(jnp.asarray(gathered[r])))
    return _maybe_task(tensor_list, sync_op)


def reduce(tensor: Tensor, dst: int, op=ReduceOp.SUM, group=None,
           sync_op=True):
    group = group or _default_group()
    all_reduce(tensor, op=op, group=group)
    return _maybe_task(tensor, sync_op)


def scatter(tensor: Tensor, tensor_list=None, src=0, group=None, sync_op=True):
    group = group or _default_group()
    if group.nranks <= 1 or not _multi_process():
        if tensor_list:
            tensor._value = tensor_list[0]._value
        return _maybe_task(tensor, sync_op)
    me = group.rank
    if tensor_list is not None:
        stacked = jnp.stack([t._value for t in tensor_list])
    else:
        stacked = jnp.zeros((group.nranks,) + tuple(tensor.shape),
                            tensor._value.dtype)
    gathered = _allgather_host(stacked)  # (world, n, ...)
    tensor._value = jnp.asarray(gathered[src][me])
    return _maybe_task(tensor, sync_op)


def alltoall(in_tensor_list, out_tensor_list, group=None, sync_op=True):
    """~ collective.py alltoall:1738 (the MoE global_scatter substrate)."""
    group = group or _default_group()
    if group.nranks <= 1 or not _multi_process():
        out_tensor_list.extend(Tensor(t._value) for t in in_tensor_list)
        return _maybe_task(out_tensor_list, sync_op)
    stacked = jnp.stack([t._value for t in in_tensor_list])
    gathered = _allgather_host(stacked)  # (world, n, ...)
    me = group.rank
    for r in group.ranks:
        out_tensor_list.append(Tensor(jnp.asarray(gathered[r][me])))
    return _maybe_task(out_tensor_list, sync_op)


def send(tensor: Tensor, dst: int, group=None, sync_op=True):
    """p2p via gather (host rendezvous) — eager-mode only; compiled paths use
    ppermute inside jit (see parallel/pipeline)."""
    group = group or _default_group()
    if not _multi_process():
        _p2p_buffer.append(tensor._value)
        return _maybe_task(tensor, sync_op)
    _allgather_host(tensor._value)
    return _maybe_task(tensor, sync_op)


_p2p_buffer: list = []


def recv(tensor: Tensor, src: int, group=None, sync_op=True):
    group = group or _default_group()
    if not _multi_process():
        if _p2p_buffer:
            tensor._value = _p2p_buffer.pop(0)
        return _maybe_task(tensor, sync_op)
    gathered = _allgather_host(tensor._value)
    tensor._value = jnp.asarray(gathered[src])
    return _maybe_task(tensor, sync_op)


def barrier(group=None):
    """~ collective.py barrier:277."""
    if _multi_process():
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu_barrier")


def wait(tensor: Tensor, group=None, use_calc_stream=True):
    """~ collective.py wait:440 — XLA has no user streams; block instead."""
    from ..core.sync import hard_sync
    hard_sync(tensor._value)


def get_rank(group=None) -> int:
    if group is not None:
        return group.rank
    return _env.get_rank()


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    return _env.get_world_size()


def destroy_process_group(group=None):
    if group is None:
        _groups.clear()
    else:
        _groups.pop(group.id, None)


# ---- compiled collective helpers (the perf path) ---------------------------
def psum_in_jit(x, axis_name: str):
    """For use inside shard_map/pjit programs."""
    return jax.lax.psum(x, axis_name)


def split(x, num_partitions, rank=None, axis=0):
    """~ paddle.distributed.split (collective.py:1525) static helper."""
    rank = rank if rank is not None else _env.get_rank()
    from ..ops.manipulation import split as _split
    parts = _split(x, num_partitions, axis)
    return parts[rank % num_partitions]
