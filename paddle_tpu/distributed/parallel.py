"""DataParallel wrapper.

~ python/paddle/fluid/dygraph/parallel.py:413 (DataParallel) + the
EagerReducer (distributed/collective/reducer.h:86). TPU-native difference:
there is no bucketed-allreduce reducer — in the compiled path, gradient
psum is inserted by XLA when the train step runs under pjit with the batch
sharded on the "data" axis, and the latency-hiding scheduler overlaps it.
This wrapper provides (a) eager-mode grad sync after backward for script
parity, (b) the sharding annotations for the compiled path.
"""
from __future__ import annotations

from ..autograd import no_grad
from ..nn.layer.layers import Layer
from . import collective as C
from . import env as _env


class DataParallel(Layer):
    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.group = group
        self.comm_buffer_size = comm_buffer_size
        self.find_unused_parameters = find_unused_parameters
        self._grad_sync_enabled = True
        # mark param sharding: replicated across "data" axis (GSPMD)
        for p in layers.parameters():
            if getattr(p, "sharding_spec", None) is None:
                p.sharding_spec = None  # replicated
        # fire grad sync from backward() itself, like the reference's
        # EagerReducer hooks (reducer.h:86) — user code never has to call
        # apply_collective_grads by hand
        import weakref

        from ..autograd.tape import register_post_backward_hook
        ref = weakref.ref(self)

        def _sync():
            dp = ref()
            if dp is None:
                self._hook.remove()
                return
            if dp._grad_sync_enabled:
                dp.apply_collective_grads()

        self._hook = register_post_backward_hook(_sync)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def no_sync(self):
        """Context manager suppressing grad sync (grad accumulation), ~
        fluid/dygraph/parallel.py DataParallel.no_sync."""
        import contextlib

        @contextlib.contextmanager
        def ctx():
            prev = self._grad_sync_enabled
            self._grad_sync_enabled = False
            try:
                yield
            finally:
                self._grad_sync_enabled = prev

        return ctx()

    @no_grad()
    def apply_collective_grads(self):
        """Eager DP grad averaging in fused buckets
        (~ Reducer::FusedAllReduceSchedule with comm_buffer_size_MB
        grouping — one collective per dtype bucket, not per tensor)."""
        world = C.get_world_size(self.group)
        if world <= 1 or not C._multi_process():
            return
        from ..core.selected_rows import SelectedRows
        from .fleet.utils.internal_storage import fused_all_reduce
        from ..core.tensor import Tensor
        sparse = [p for p in self._layers.parameters()
                  if isinstance(p._grad, SelectedRows)]
        params = [p for p in self._layers.parameters()
                  if p._grad is not None
                  and not isinstance(p._grad, SelectedRows)]
        for p in sparse:
            p._grad = self._sync_selected_rows(p._grad, world)
        if not params:
            return

        def collective(flat):
            t = Tensor(flat)
            C.all_reduce(t, group=self.group)
            return t._value / world

        reduced = fused_all_reduce([p._grad._value for p in params],
                                   collective,
                                   self.comm_buffer_size * 1024 * 1024)
        for p, g in zip(params, reduced):
            p._grad._value = g.astype(p._grad._value.dtype)

    def _sync_selected_rows(self, sr, world):
        """Average a SelectedRows grad across DP ranks (~ the reference
        Reducer's sparse allreduce: allgather rows+values, concatenate).
        Row counts differ per rank, so pad to the global max first."""
        import jax.numpy as jnp
        import numpy as np

        from ..core.selected_rows import SelectedRows
        from ..core.tensor import Tensor
        merged = sr.merge()
        n = merged.rows.shape[0]
        cnt = Tensor(jnp.asarray([n], jnp.int32))
        C.all_reduce(cnt, op=C.ReduceOp.MAX, group=self.group)
        n_max = int(np.asarray(cnt.numpy())[0])
        pad = n_max - n
        # pad with row 0 / zero values: contributes nothing after merge
        rows = jnp.pad(merged.rows, (0, pad))
        vals = jnp.pad(merged.values, ((0, pad), (0, 0)))
        rows_l, vals_l = [], []
        C.all_gather(rows_l, Tensor(rows), group=self.group)
        C.all_gather(vals_l, Tensor(vals), group=self.group)
        g_rows = jnp.concatenate([t._value for t in rows_l])
        g_vals = jnp.concatenate([t._value for t in vals_l])
        return SelectedRows(g_rows, g_vals / world, sr.height)

    # delegate the Layer surface to the wrapped model
    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, st, **kw):
        return self._layers.set_state_dict(st, **kw)

    def train(self):
        self._layers.train()
        self.training = True
        return self

    def eval(self):
        self._layers.eval()
        self.training = False
        return self

    def scale_loss(self, loss):
        return loss
