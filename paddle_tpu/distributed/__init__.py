"""paddle_tpu.distributed.

~ python/paddle/distributed/: collective API, fleet facade, hybrid topology,
parallel layers, launch. See SURVEY.md §2.2/2.3/2.5 for the reference map.
"""
from .env import (  # noqa: F401
    ParallelEnv, get_rank, get_world_size, init_parallel_env, is_initialized,
)
from .collective import (  # noqa: F401
    ReduceOp, Task, all_gather, all_reduce, alltoall, barrier, broadcast,
    destroy_process_group, get_group, new_group, recv, reduce, scatter, send,
    split, wait,
)
from .topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup, ParallelGroup, build_mesh,
    get_global_mesh, get_hybrid_communicate_group, set_global_mesh,
    set_hybrid_communicate_group,
)
from . import fleet  # noqa: F401
from .store import TCPStore  # noqa: F401
from .reshard import reshard, reshard_like  # noqa: F401
from .parallel import DataParallel  # noqa: F401
from . import sharding  # noqa: F401
from . import checkpoint  # noqa: F401


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """~ paddle.distributed.spawn (distributed/spawn.py) — multiprocessing
    entry for same-host multi-process runs (one process per simulated rank;
    CPU backend). Each child gets the PADDLE_* env contract."""
    import multiprocessing as mp
    import os

    if nprocs == -1:
        nprocs = int(os.environ.get("PADDLE_WORLD_SIZE", "1"))
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        env = {"PADDLE_GLOBAL_RANK": str(rank),
               "PADDLE_WORLD_SIZE": str(nprocs),
               "PADDLE_LOCAL_RANK": str(rank)}
        p = ctx.Process(target=_spawn_entry, args=(func, args, env),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError(f"spawned rank failed: exit {p.exitcode}")
    return procs


def _spawn_entry(func, args, env):
    import os
    os.environ.update(env)
    func(*args)
from . import launch  # noqa: F401
from .entry_attr import (  # noqa: F401
    CountFilterEntry, InMemoryDataset, ParallelMode, ProbabilityEntry,
    QueueDataset, ShowClickEntry,
)


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """~ paddle.distributed.gloo_init_parallel_env: CPU-collective bootstrap.
    Maps to the same coordinator init as init_parallel_env (jax.distributed
    is transport-agnostic; gloo's role — CPU rendezvous/barrier — is played
    by the coordinator service)."""
    import os
    os.environ.setdefault("PADDLE_TRAINER_ID", str(rank_id))
    os.environ.setdefault("PADDLE_TRAINERS_NUM", str(rank_num))
    os.environ.setdefault("PADDLE_MASTER", server_endpoint)
    from .parallel import init_parallel_env
    return init_parallel_env()


def gloo_barrier():
    """~ paddle.distributed.gloo_barrier — host-level barrier."""
    from .collective import barrier
    return barrier()


def gloo_release():
    """~ paddle.distributed.gloo_release — tear down CPU rendezvous state."""
    return None
from . import fleet_executor  # noqa: F401
from .fleet_executor import DistModel, DistModelConfig, FleetExecutor  # noqa
from . import passes  # noqa: F401

from . import metric  # noqa: F401,E402
