"""Launcher entry: python -m paddle_tpu.distributed.launch train.py

~ distributed/launch/main.py:18 + controllers/collective.py:32 (build_pod)
+ job/container.py:97 (subprocess per rank) + controller watch loop.

Per-node it spawns one process per local rank with the env contract
(PADDLE_MASTER, PADDLE_GLOBAL_RANK, PADDLE_LOCAL_RANK, PADDLE_WORLD_SIZE,
PADDLE_TRAINER_ENDPOINTS); multi-node rendezvous goes through HTTPMaster
(node 0). jax.distributed.initialize in the trainer (init_parallel_env)
then uses PADDLE_MASTER as the coordinator. Elastic mode watches children
and relaunches the pod on failure (~ ElasticManager, bounded restarts).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List

from .master import HTTPMaster


def _parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--master", default=None,
                   help="host:port of node-0 KV (defaults to localhost)")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--nproc_per_node", type=int, default=None)
    p.add_argument("--log_dir", default=None)
    p.add_argument("--elastic_level", type=int, default=0,
                   help=">0: restart pod on child failure (max_restart times)")
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--np", dest="np_range", default=None,
                   help="MIN:MAX elastic node range; membership changes "
                        "within the range relaunch trainers with rewritten "
                        "rank envs (~ elastic/manager.py:34)")
    p.add_argument("--elastic_node_id", default=None,
                   help="stable node identity in the elastic membership "
                        "registry (default: host:node_rank)")
    p.add_argument("--devices", default=None,
                   help="comma ids exported as PADDLE_VISIBLE_DEVICES")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


class Container:
    """One local rank (~ launch/job/container.py)."""

    def __init__(self, cmd: List[str], env: dict, log_path: str | None):
        self.cmd = cmd
        self.env = env
        self.log_path = log_path
        self.proc = None
        self._log_f = None

    def start(self):
        out = None
        if self.log_path:
            os.makedirs(os.path.dirname(self.log_path), exist_ok=True)
            self._log_f = open(self.log_path, "w")
            out = self._log_f
        self.proc = subprocess.Popen(
            self.cmd, env={**os.environ, **self.env}, stdout=out,
            stderr=subprocess.STDOUT if out else None)

    def alive(self):
        return self.proc is not None and self.proc.poll() is None

    @property
    def returncode(self):
        return self.proc.poll() if self.proc else None

    def terminate(self):
        if self.alive():
            self.proc.terminate()
            try:
                self.proc.wait(10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        if self._log_f:
            self._log_f.close()
            self._log_f = None


def build_pod(args, n_nodes=None, node_index=None,
              endpoints_override=None) -> List[Container]:
    """~ CollectiveController.build_pod (controllers/collective.py:32).

    ``n_nodes``/``node_index`` override the static --nnodes/--node_rank
    when elastic membership decides the pod size (~ manager.py:130's
    rank-env rewrite on scale events); ``endpoints_override`` then carries
    the endpoint list assembled from the membership registry (each node's
    published IP), since the static HTTPMaster sync expects a fixed node
    count.
    """
    nproc = args.nproc_per_node
    if nproc is None:
        nproc = 1
    nn = args.nnodes if n_nodes is None else n_nodes
    ni = args.node_rank if node_index is None else node_index
    world = nn * nproc
    master_ep = args.master or "127.0.0.1:34782"

    if endpoints_override is not None:
        endpoints = endpoints_override
    elif nn > 1 and n_nodes is None:
        master = HTTPMaster(master_ep, is_host=ni == 0)
        import socket
        my_ip = socket.gethostbyname(socket.gethostname())
        peers = master.sync_peers("peers", f"{my_ip}:{nproc}", ni, nn)
        endpoints = ",".join(peers)
    else:
        # single node: one endpoint per local rank (reference contract —
        # PADDLE_TRAINER_ENDPOINTS is always present, collective.py:83-91)
        host, port = (master_ep.split(":") + ["34782"])[:2]
        endpoints = ",".join(f"{host}:{int(port) + 100 + r}"
                             for r in range(world))

    containers = []
    for local_rank in range(nproc):
        rank = ni * nproc + local_rank
        env = {
            "PADDLE_MASTER": master_ep,
            "PADDLE_COORDINATOR": master_ep,
            "PADDLE_GLOBAL_RANK": str(rank),
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_WORLD_SIZE": str(world),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_NNODES": str(args.nnodes),
        }
        if endpoints:
            env["PADDLE_TRAINER_ENDPOINTS"] = endpoints
        if args.devices:
            env["PADDLE_VISIBLE_DEVICES"] = args.devices
        log = None
        if args.log_dir:
            log = os.path.join(args.log_dir, f"workerlog.{local_rank}")
        containers.append(Container(
            [sys.executable, args.training_script]
            + args.training_script_args, env, log))
    return containers


def watch(containers: List[Container], poll: float = 2.0,
          rescale_check=None):
    """~ controller.watch: exit 0 when all done, kill pod on any failure.
    With ``rescale_check`` (elastic), returns "scale" when the membership
    watcher decides the pod must relaunch at a new world size."""
    while True:
        codes = [c.returncode for c in containers]
        if any(c is not None and c != 0 for c in codes):
            for c in containers:
                c.terminate()
            return next(c for c in codes if c)
        if all(c == 0 for c in codes):
            return 0
        if rescale_check is not None and rescale_check():
            for c in containers:
                c.terminate()
            return "scale"
        time.sleep(poll)


def _elastic_manager(args):
    """Membership registry for --np MIN:MAX (~ ElasticManager over etcd,
    elastic/manager.py:34 — here over the TCPStore)."""
    from ..fleet.elastic import ElasticManager
    from ..store import TCPStore
    min_np, _, max_np = args.np_range.partition(":")
    min_np = int(min_np)
    max_np = int(max_np or min_np)
    master_ep = args.master or "127.0.0.1:34782"
    host, port = (master_ep.split(":") + ["34782"])[:2]
    # the membership store lives beside the trainer rendezvous port
    store = TCPStore(host, int(port) + 7, is_master=args.node_rank == 0)
    node_id = args.elastic_node_id or f"{host}:{args.node_rank}"
    mgr = ElasticManager(store, node_id, (min_np, max_np),
                         heartbeat_interval=0.5, dead_after=3.0)
    mgr.start()
    # publish this node's IP so every pod can assemble the true endpoint
    # list from the live membership (the static HTTPMaster sync can't —
    # it expects a fixed node count)
    import socket
    try:
        my_ip = socket.gethostbyname(socket.gethostname())
    except OSError:
        my_ip = "127.0.0.1"
    store.set(f"__node_ip__/{node_id}", my_ip)
    return mgr, node_id, min_np, max_np


def _elastic_endpoints(manager, alive, nproc, base_port):
    """PADDLE_TRAINER_ENDPOINTS from live membership: each node's
    published IP, nproc consecutive ports per node in sorted-member
    order (the reference's rank-env rewrite, manager.py:130)."""
    eps = []
    for idx, node in enumerate(alive):
        ip = manager.store.get(f"__node_ip__/{node}")
        ip = ip.decode() if ip else "127.0.0.1"
        for lr in range(nproc):
            eps.append(f"{ip}:{base_port + 100 + idx * nproc + lr}")
    return ",".join(eps)


def launch(argv=None) -> int:
    args = _parse_args(argv)
    manager = None
    if args.np_range:
        manager, node_id, min_np, max_np = _elastic_manager(args)
        pending = {"flag": False}
        manager.watch(lambda old, new: pending.update(flag=True))
    restarts = 0
    cur = {"n_nodes": None, "node_index": None}
    while True:
        if manager is not None:
            # effective pod size from live membership, clamped to the
            # range; this node must ALSO be in the alive list — assuming
            # index 0 while absent would duplicate the real rank-0 pod
            deadline = time.time() + 60.0
            alive = manager.alive_members()
            while (len(alive) < min_np or node_id not in alive) \
                    and time.time() < deadline:
                time.sleep(0.5)
                alive = manager.alive_members()
            if len(alive) < min_np:
                print(f"[launch] elastic hold: {len(alive)} < np min "
                      f"{min_np}", file=sys.stderr)
                return 1
            if node_id not in alive:
                print(f"[launch] elastic error: this node ({node_id}) "
                      f"missing from membership {alive}", file=sys.stderr)
                return 1
            n_nodes = min(len(alive), max_np)
            node_index = alive.index(node_id)
            pending["flag"] = False
            cur.update(n_nodes=n_nodes, node_index=node_index)
            master_ep = args.master or "127.0.0.1:34782"
            base_port = int((master_ep.split(":") + ["34782"])[1])
            containers = build_pod(
                args, n_nodes=n_nodes, node_index=node_index,
                endpoints_override=_elastic_endpoints(
                    manager, alive[:n_nodes], args.nproc_per_node or 1,
                    base_port))
        else:
            containers = build_pod(args)
        for c in containers:
            c.start()

        def handler(sig, frame):
            for c in containers:
                c.terminate()
            sys.exit(1)
        signal.signal(signal.SIGINT, handler)
        signal.signal(signal.SIGTERM, handler)

        def rescale_check():
            # relaunch only when the EFFECTIVE size/rank changes (a join
            # beyond max_np or a leave still >= current view is a no-op)
            if not pending["flag"]:
                return False
            alive = manager.alive_members()
            if node_id not in alive:
                # transient self-absence (slow heartbeat): never rescale
                # on it — assuming an index would duplicate another node's
                # rank block
                return False
            n_new = min(len(alive), max_np)
            idx_new = alive.index(node_id)
            if n_new >= min_np and (n_new != cur["n_nodes"]
                                    or idx_new != cur["node_index"]):
                return True
            pending["flag"] = False
            return False

        code = watch(containers,
                     rescale_check=rescale_check if manager else None)
        if code == "scale":
            print(f"[launch] elastic scale: membership now "
                  f"{manager.alive_members()} -> relaunch with rewritten "
                  f"rank envs", file=sys.stderr)
            continue  # scale events do not consume the restart budget
        if code == 0:
            if manager is not None:
                manager.stop()
            return 0
        restarts += 1
        if args.elastic_level <= 0 or restarts > args.max_restart:
            if manager is not None:
                manager.stop()
            return code
        print(f"[launch] pod failed (exit {code}); elastic restart "
              f"{restarts}/{args.max_restart}", file=sys.stderr)
        time.sleep(2.0)


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
