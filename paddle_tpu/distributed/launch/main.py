"""Launcher entry: python -m paddle_tpu.distributed.launch train.py

~ distributed/launch/main.py:18 + controllers/collective.py:32 (build_pod)
+ job/container.py:97 (subprocess per rank) + controller watch loop.

Per-node it spawns one process per local rank with the env contract
(PADDLE_MASTER, PADDLE_GLOBAL_RANK, PADDLE_LOCAL_RANK, PADDLE_WORLD_SIZE,
PADDLE_TRAINER_ENDPOINTS); multi-node rendezvous goes through HTTPMaster
(node 0). jax.distributed.initialize in the trainer (init_parallel_env)
then uses PADDLE_MASTER as the coordinator. Elastic mode watches children
and relaunches the pod on failure (~ ElasticManager, bounded restarts).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List

from .master import HTTPMaster


def _parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--master", default=None,
                   help="host:port of node-0 KV (defaults to localhost)")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--nproc_per_node", type=int, default=None)
    p.add_argument("--log_dir", default=None)
    p.add_argument("--elastic_level", type=int, default=0,
                   help=">0: restart pod on child failure (max_restart times)")
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--devices", default=None,
                   help="comma ids exported as PADDLE_VISIBLE_DEVICES")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


class Container:
    """One local rank (~ launch/job/container.py)."""

    def __init__(self, cmd: List[str], env: dict, log_path: str | None):
        self.cmd = cmd
        self.env = env
        self.log_path = log_path
        self.proc = None
        self._log_f = None

    def start(self):
        out = None
        if self.log_path:
            os.makedirs(os.path.dirname(self.log_path), exist_ok=True)
            self._log_f = open(self.log_path, "w")
            out = self._log_f
        self.proc = subprocess.Popen(
            self.cmd, env={**os.environ, **self.env}, stdout=out,
            stderr=subprocess.STDOUT if out else None)

    def alive(self):
        return self.proc is not None and self.proc.poll() is None

    @property
    def returncode(self):
        return self.proc.poll() if self.proc else None

    def terminate(self):
        if self.alive():
            self.proc.terminate()
            try:
                self.proc.wait(10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        if self._log_f:
            self._log_f.close()
            self._log_f = None


def build_pod(args) -> List[Container]:
    """~ CollectiveController.build_pod (controllers/collective.py:32)."""
    nproc = args.nproc_per_node
    if nproc is None:
        nproc = 1
    world = args.nnodes * nproc
    master_ep = args.master or "127.0.0.1:34782"

    if args.nnodes > 1:
        master = HTTPMaster(master_ep, is_host=args.node_rank == 0)
        import socket
        my_ip = socket.gethostbyname(socket.gethostname())
        peers = master.sync_peers("peers", f"{my_ip}:{nproc}",
                                  args.node_rank, args.nnodes)
        endpoints = ",".join(peers)
    else:
        # single node: one endpoint per local rank (reference contract —
        # PADDLE_TRAINER_ENDPOINTS is always present, collective.py:83-91)
        host, port = (master_ep.split(":") + ["34782"])[:2]
        endpoints = ",".join(f"{host}:{int(port) + 100 + r}"
                             for r in range(world))

    containers = []
    for local_rank in range(nproc):
        rank = args.node_rank * nproc + local_rank
        env = {
            "PADDLE_MASTER": master_ep,
            "PADDLE_COORDINATOR": master_ep,
            "PADDLE_GLOBAL_RANK": str(rank),
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_WORLD_SIZE": str(world),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_NNODES": str(args.nnodes),
        }
        if endpoints:
            env["PADDLE_TRAINER_ENDPOINTS"] = endpoints
        if args.devices:
            env["PADDLE_VISIBLE_DEVICES"] = args.devices
        log = None
        if args.log_dir:
            log = os.path.join(args.log_dir, f"workerlog.{local_rank}")
        containers.append(Container(
            [sys.executable, args.training_script]
            + args.training_script_args, env, log))
    return containers


def watch(containers: List[Container], poll: float = 2.0) -> int:
    """~ controller.watch: exit 0 when all done, kill pod on any failure."""
    while True:
        codes = [c.returncode for c in containers]
        if any(c is not None and c != 0 for c in codes):
            for c in containers:
                c.terminate()
            return next(c for c in codes if c)
        if all(c == 0 for c in codes):
            return 0
        time.sleep(poll)


def launch(argv=None) -> int:
    args = _parse_args(argv)
    restarts = 0
    while True:
        containers = build_pod(args)
        for c in containers:
            c.start()

        def handler(sig, frame):
            for c in containers:
                c.terminate()
            sys.exit(1)
        signal.signal(signal.SIGINT, handler)
        signal.signal(signal.SIGTERM, handler)

        code = watch(containers)
        if code == 0:
            return 0
        restarts += 1
        if args.elastic_level <= 0 or restarts > args.max_restart:
            return code
        print(f"[launch] pod failed (exit {code}); elastic restart "
              f"{restarts}/{args.max_restart}", file=sys.stderr)
        time.sleep(2.0)


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
