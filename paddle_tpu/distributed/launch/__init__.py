"""Cluster launcher. ~ python/paddle/distributed/launch/ (SURVEY.md §3.5)."""
from .main import launch, main  # noqa: F401
