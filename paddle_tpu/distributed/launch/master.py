"""Rendezvous master: HTTP key-value store.

~ distributed/launch/controllers/master.py:66 (HTTPMaster — node-0-hosted
KV used by peers to exchange endpoints; sync_peers:129, heartbeat:232).
The ETCD variant is out of scope (external service); the KV contract is the
same one jax.distributed's coordinator fills for collective init — this
master only orchestrates process bring-up.
"""
from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class KVHandler(BaseHTTPRequestHandler):
    store: dict = {}
    lock = threading.Lock()

    def log_message(self, *a):
        pass

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        with self.lock:
            KVHandler.store[self.path] = value
        self.send_response(200)
        self.end_headers()

    def do_GET(self):
        with self.lock:
            if self.path == "/__all__":
                body = json.dumps(
                    {k: v.decode() for k, v in KVHandler.store.items()}
                ).encode()
                self.send_response(200)
                self.end_headers()
                self.wfile.write(body)
                return
            value = KVHandler.store.get(self.path)
        if value is None:
            self.send_response(404)
            self.end_headers()
        else:
            self.send_response(200)
            self.end_headers()
            self.wfile.write(value)

    def do_DELETE(self):
        with self.lock:
            KVHandler.store.pop(self.path, None)
        self.send_response(200)
        self.end_headers()


class HTTPMaster:
    """Node-0 hosted KV (~ controllers/master.py:66)."""

    def __init__(self, endpoint: str, is_host: bool):
        self.endpoint = endpoint
        self.is_host = is_host
        self.server = None
        if is_host:
            host, port = endpoint.split(":")
            self.server = ThreadingHTTPServer(("0.0.0.0", int(port)),
                                              KVHandler)
            t = threading.Thread(target=self.server.serve_forever,
                                 daemon=True)
            t.start()

    def put(self, key: str, value: str, retry_for: float = 60.0):
        """PUT with connection retry: non-host nodes race the host's
        server bind (a node-1 launcher can reach here before node 0 has
        bound the port — without retry that start-order race crashes the
        pod)."""
        req = urllib.request.Request(
            f"http://{self.endpoint}/{key}", data=value.encode(),
            method="PUT")
        deadline = time.time() + retry_for
        while True:
            try:
                urllib.request.urlopen(req, timeout=10)
                return
            except urllib.error.HTTPError:
                raise  # the server answered: a real error, not the race
            except (ConnectionError, urllib.error.URLError):
                if time.time() >= deadline:
                    raise
                time.sleep(0.5)

    def get(self, key: str):
        try:
            with urllib.request.urlopen(
                    f"http://{self.endpoint}/{key}", timeout=10) as r:
                return r.read().decode()
        except Exception:
            return None

    def sync_peers(self, prefix: str, my_value: str, rank: int, size: int,
                   timeout: float = 300.0):
        """~ master.py sync_peers:129 — publish self, wait for all."""
        self.put(f"{prefix}/{rank}", my_value)
        deadline = time.time() + timeout
        while time.time() < deadline:
            values = [self.get(f"{prefix}/{i}") for i in range(size)]
            if all(v is not None for v in values):
                return values
            time.sleep(0.5)
        raise TimeoutError(f"sync_peers: not all {size} peers reported")

    def stop(self):
        if self.server:
            self.server.shutdown()
