"""Role discovery.

~ fleet/base/role_maker.py (PaddleCloudRoleMaker): derive rank/role from
the launch env contract.
"""
from __future__ import annotations

import os


class Role:
    WORKER = 1
    SERVER = 2


class PaddleCloudRoleMaker:
    def __init__(self, is_collective: bool = True, **kwargs):
        self._is_collective = is_collective

    def _worker_index(self) -> int:
        return int(os.environ.get("PADDLE_GLOBAL_RANK",
                                  os.environ.get("PADDLE_TRAINER_ID", "0")))

    def _worker_num(self) -> int:
        return int(os.environ.get("PADDLE_WORLD_SIZE",
                                  os.environ.get("PADDLE_TRAINERS_NUM", "1")))

    def _is_first_worker(self) -> bool:
        return self._worker_index() == 0

    def _role(self):
        return Role.SERVER if os.environ.get("PADDLE_ROLE") == "server" \
            else Role.WORKER

    worker_index = _worker_index
    worker_num = _worker_num
    is_first_worker = _is_first_worker

    def _get_trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None, **kw):
        super().__init__()
        self._cur = current_id
        self._n = worker_num

    def _worker_index(self):
        return self._cur

    def _worker_num(self):
        return self._n

    worker_index = _worker_index
    worker_num = _worker_num
