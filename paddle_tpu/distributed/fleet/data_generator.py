"""Fleet data generators (PS file-dataset pipeline).

~ python/paddle/distributed/fleet/data_generator/data_generator.py:
user subclasses override ``generate_sample(line)`` returning an iterator
of (slot_name, feasign_list) pairs; the base class streams stdin/memory
lines into the MultiSlot text protocol that the reference's C++ DataFeed
parses (framework/data_feed.cc). The TPU build's InMemoryDataset/
QueueDataset wrappers consume the same protocol, and ``to_arrays`` bridges
generated batches straight to numpy for DataLoader-style use.
"""
from __future__ import annotations

import sys
from typing import Dict, Iterator, List, Tuple

import numpy as np

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    """Base generator: inherit + override generate_sample."""

    def __init__(self):
        self.batch_size_ = 1
        self._proto_info = None

    def set_batch(self, batch_size: int):
        self.batch_size_ = batch_size

    # -- user hooks ------------------------------------------------------
    def generate_sample(self, line):
        """Returns a zero-arg iterator function yielding
        [(slot_name, [feasigns...]), ...] per sample."""
        raise NotImplementedError(
            "subclass DataGenerator and override generate_sample")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    # -- drivers ---------------------------------------------------------
    def run_from_stdin(self):
        """stdin lines -> protocol lines on stdout (the PS trainer pipe)."""
        batch = []
        for line in sys.stdin:
            it = self.generate_sample(line)
            if it is None:
                continue
            for sample in it():
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) == self.batch_size_:
                    self._flush(batch, sys.stdout)
                    batch = []
        if batch:
            self._flush(batch, sys.stdout)

    def run_from_memory(self, lines=None):
        """Returns the protocol lines for in-memory lines (tests/datasets)."""
        out: List[str] = []
        batch = []
        for line in (lines if lines is not None else [None]):
            it = self.generate_sample(line)
            if it is None:
                continue
            for sample in it():
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) == self.batch_size_:
                    out.extend(self._render(batch))
                    batch = []
        if batch:
            out.extend(self._render(batch))
        return out

    def _flush(self, batch, fh):
        for ln in self._render(batch):
            fh.write(ln + "\n")

    def _render(self, batch) -> List[str]:
        lines = []
        for sample in self.generate_batch(batch)():
            lines.append(self._gen_str(sample))
        return lines

    def _gen_str(self, sample) -> str:
        raise NotImplementedError

    # -- numpy bridge ----------------------------------------------------
    @staticmethod
    def to_arrays(proto_lines: List[str]) -> List[Dict[str, np.ndarray]]:
        """Parse MultiSlot protocol lines back into per-sample
        {slot: values} dicts (the DataFeed parse, host-side)."""
        out = []
        for ln in proto_lines:
            toks = ln.split()
            i = 0
            rec: Dict[str, np.ndarray] = {}
            slot_idx = 0
            while i < len(toks):
                n = int(toks[i])
                vals = toks[i + 1:i + 1 + n]
                i += 1 + n
                arr = (np.asarray([float(v) for v in vals], np.float32)
                       if any("." in v for v in vals)
                       else np.asarray([int(v) for v in vals], np.int64))
                rec[f"slot_{slot_idx}"] = arr
                slot_idx += 1
            out.append(rec)
        return out


class MultiSlotDataGenerator(DataGenerator):
    """~ MultiSlotDataGenerator: sample = [(slot, [ints/floats]), ...]
    rendered as `<n> v1..vn <n> v1..vn ...` per line."""

    def _gen_str(self, sample) -> str:
        parts = []
        for _slot, feasigns in sample:
            parts.append(str(len(feasigns)))
            parts.extend(str(f) for f in feasigns)
        return " ".join(parts)


class MultiSlotStringDataGenerator(DataGenerator):
    """~ MultiSlotStringDataGenerator: feasigns already strings."""

    def _gen_str(self, sample) -> str:
        parts = []
        for _slot, feasigns in sample:
            parts.append(str(len(feasigns)))
            parts.extend(feasigns)
        return " ".join(parts)
