"""Fleet singleton: init / distributed_model / distributed_optimizer.

~ fleet/base/fleet_base.py:139,206,880,937,1443.
"""
from __future__ import annotations

from typing import Optional

from ...nn.layer.layers import Layer
from .. import env as _env
from ..parallel import DataParallel
from ..topology import (CommunicateTopology, HybridCommunicateGroup,
                        set_hybrid_communicate_group,
                        get_hybrid_communicate_group as _get_hcg)
from .distributed_strategy import DistributedStrategy


class Fleet:
    def __init__(self):
        self._strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._is_collective = True

    def init(self, role_maker=None, is_collective=True, strategy=None):
        """~ fleet_base.py init:206."""
        self._strategy = strategy or DistributedStrategy()
        self._is_collective = is_collective
        _env.init_parallel_env()
        hc = self._strategy.hybrid_configs
        dims = {"data": hc.dp_degree, "pipe": hc.pp_degree,
                "sharding": hc.sharding_degree, "sep": hc.get("sep_degree", 1),
                "model": hc.mp_degree}
        # fill dp automatically to consume the world (reference behavior)
        world = _env.get_world_size()
        import numpy as np
        known = int(np.prod([v for k, v in dims.items() if k != "data"]))
        if dims["data"] * known != world and world % known == 0 and world > 1:
            dims["data"] = world // known
        topo = CommunicateTopology(
            ["data", "pipe", "sharding", "sep", "model"],
            [dims["data"], dims["pipe"], dims["sharding"], dims["sep"],
             dims["model"]])
        if topo.world_size() == world or world == 1:
            self._hcg = HybridCommunicateGroup(topo)
            set_hybrid_communicate_group(self._hcg)
        return self

    def get_hybrid_communicate_group(self):
        return self._hcg or _get_hcg()

    @property
    def worker_index(self):
        return _env.get_rank()

    @property
    def worker_num(self):
        return _env.get_world_size()

    def is_first_worker(self):
        return _env.get_rank() == 0

    def barrier_worker(self):
        from .. import collective as C
        C.barrier()

    def distributed_model(self, model: Layer):
        """~ fleet_base.py distributed_model:937 — wrapper selection."""
        hcg = self.get_hybrid_communicate_group()
        strategy = self._strategy or DistributedStrategy()
        if hcg is not None and hcg.get_pipe_parallel_world_size() > 1:
            from .meta_parallel.pipeline_parallel import PipelineParallel
            return PipelineParallel(model, hcg, strategy)
        if hcg is not None and hcg.get_model_parallel_world_size() > 1:
            from .meta_parallel.tensor_parallel import TensorParallel
            return TensorParallel(model, hcg, strategy)
        if _env.get_world_size() > 1 or (
                hcg and hcg.get_data_parallel_world_size() > 1):
            return DataParallel(model,
                                group=hcg.get_data_parallel_group()
                                if hcg else None)
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        """~ fleet_base.py distributed_optimizer:880."""
        if strategy is not None:
            self._strategy = strategy
        hcg = self.get_hybrid_communicate_group()
        if hcg is not None and (hcg.get_model_parallel_world_size() > 1
                                or hcg.get_pipe_parallel_world_size() > 1):
            from .meta_parallel.hybrid_parallel_optimizer import (
                HybridParallelOptimizer)
            return HybridParallelOptimizer(optimizer, hcg,
                                           self._strategy
                                           or DistributedStrategy())
        return optimizer

    def state_dict(self):
        return {}


fleet = Fleet()

# module-level facade (paddle.distributed.fleet.init style)
def init(role_maker=None, is_collective=True, strategy=None):
    return fleet.init(role_maker, is_collective, strategy)


def distributed_model(model):
    return fleet.distributed_model(model)


def distributed_optimizer(optimizer, strategy=None):
    return fleet.distributed_optimizer(optimizer, strategy)


def get_hybrid_communicate_group():
    return fleet.get_hybrid_communicate_group()


def worker_index():
    return fleet.worker_index


def worker_num():
    return fleet.worker_num


def is_first_worker():
    return fleet.is_first_worker()
