"""DistributedStrategy.

~ python/paddle/distributed/fleet/base/distributed_strategy.py backed by
framework/distributed_strategy.proto:277-337. One typed config tree; the
protobuf round-trip is replaced by plain dataclass-style dicts (XLA needs no
cross-language program rewriting contract).
"""
from __future__ import annotations

import copy


class _Config(dict):
    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError as e:
            raise AttributeError(k) from e

    def __setattr__(self, k, v):
        self[k] = v


class DistributedStrategy:
    def __init__(self):
        # ~ distributed_strategy.proto defaults
        self.amp = False
        self.amp_configs = _Config(
            init_loss_scaling=32768.0, custom_white_list=[],
            custom_black_list=[], use_pure_fp16=False, use_bf16=True)
        self.recompute = False
        self.recompute_configs = _Config(checkpoints=[])
        self.pipeline = False
        self.pipeline_configs = _Config(
            micro_batch_size=1, accumulate_steps=1, schedule_mode="1F1B")
        self.tensor_parallel = False
        self.tensor_parallel_configs = _Config(
            tensor_parallel_degree=1, tensor_init_seed=-1)
        self.sharding = False
        self.sharding_configs = _Config(
            sharding_degree=1, stage=1, offload=False,
            segment_broadcast_MB=32.0)
        self.hybrid_configs = _Config(
            dp_degree=1, mp_degree=1, pp_degree=1, sharding_degree=1,
            sep_degree=1)
        self.gradient_merge = False
        self.gradient_merge_configs = _Config(k_steps=1, avg=True)
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.localsgd = False
        self.fp16_allreduce = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.gradient_scale_configs = _Config(scale_strategy="avg")
        self.heter_ccl_mode = False
        self.a_sync = False
        self.a_sync_configs = _Config(k_steps=-1)
        self.auto = False
        self.semi_auto = False
        self.elastic = False

    def __setattr__(self, k, v):
        if isinstance(v, dict) and not isinstance(v, _Config):
            cur = self.__dict__.get(k)
            if isinstance(cur, _Config):
                cur.update(v)
                return
            v = _Config(v)
        object.__setattr__(self, k, v)

    def __deepcopy__(self, memo):
        new = DistributedStrategy()
        for k, v in self.__dict__.items():
            object.__setattr__(new, k, copy.deepcopy(v, memo))
        return new

    def __repr__(self):
        on = [k for k, v in self.__dict__.items() if v is True]
        return f"DistributedStrategy(enabled={on})"
