"""Heterogeneous staged PS trainer (CPU section <-> accelerator section).

~ paddle/fluid/framework/heter_pipeline_trainer.cc + heter_section_worker.cc
and the heter service (distributed/ps/service/heter_client.h,
collective/ProcessGroupHeter.h:64): embedding-dominated work runs in a
HOST-side section colocated with the parameter server (sparse pull/push on
numpy tables), while the dense math runs in an ACCELERATOR section as one
jitted step; micro-batches stream between the sections over a
length-prefixed message channel so both stay busy (the staged
producer/consumer queues of heter_section_worker).

TPU-native shape: the accelerator section's step is a single compiled
function (params, emb_rows, dense_x, labels) -> (params', loss, emb_grad)
— embedding rows enter as a dense input (so XLA never sees the sparse
lookup), and the returned row gradients ride back to the CPU section,
which pushes them into the PS sparse table's SGD/Adagrad rule.
"""
from __future__ import annotations

import socket
import threading
from typing import Callable, Iterable, Optional

import numpy as np

from ..ps import PSClient, _recv_msg, _send_msg

_STOP = "__heter_stop__"


class StageChannel:
    """Point-to-point staged message channel between two sections
    (~ the heter worker's send/recv service). Length-prefixed pickle
    frames over TCP; either endpoint may be the listener."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 listen: bool = False, timeout: float = 120.0):
        self.host = host
        if listen:
            self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._srv.bind((host, port))
            self._srv.listen(1)
            self.port = self._srv.getsockname()[1]
            self._sock: Optional[socket.socket] = None
            self._timeout = timeout
        else:
            self._srv = None
            self.port = port
            self._sock = socket.create_connection((host, port),
                                                  timeout=timeout)
        self._mu = threading.Lock()

    def _ensure(self):
        if self._sock is None:
            self._srv.settimeout(self._timeout)
            self._sock, _ = self._srv.accept()
        return self._sock

    def send(self, obj) -> None:
        with self._mu:
            _send_msg(self._ensure(), obj)

    def recv(self):
        return _recv_msg(self._ensure())

    def close(self):
        for s in (self._sock, self._srv):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass


class CpuSection:
    """Host-side stage: sparse pull -> stage send -> grad recv -> sparse
    push (~ heter_section_worker.cc RunListen/RunForward split). Keeps up
    to ``window`` micro-batches in flight toward the accelerator section
    so the PS round trips overlap device compute."""

    def __init__(self, ps: PSClient, channel: StageChannel,
                 table_id: int = 0, window: int = 2):
        self.ps = ps
        self.channel = channel
        self.table_id = table_id
        self.window = max(1, window)

    def _drain_one(self):
        out = self.channel.recv()
        if out is None:
            raise ConnectionError("heter section closed the channel")
        ids, emb_grad, loss = out
        self.ps.push_sparse(ids, emb_grad, self.table_id)
        return loss

    def run_epoch(self, batches: Iterable) -> list:
        """batches: iterable of (sparse_ids, dense_x, labels). Returns
        per-micro-batch losses (device-section order preserved)."""
        losses = []
        inflight = 0
        for ids, dense_x, labels in batches:
            rows = self.ps.pull_sparse(ids, self.table_id)
            self.channel.send((np.asarray(ids), rows,
                               None if dense_x is None
                               else np.asarray(dense_x),
                               np.asarray(labels)))
            inflight += 1
            if inflight >= self.window:
                losses.append(self._drain_one())
                inflight -= 1
        while inflight:
            losses.append(self._drain_one())
            inflight -= 1
        return losses

    def finish(self):
        self.channel.send(_STOP)


class HeterSection:
    """Accelerator-side stage: recv staged batch -> one compiled step ->
    send row grads back (~ heter_pipeline_trainer device section).

    ``train_step(params, emb_rows, dense_x, labels) -> (params, loss,
    emb_grad)`` should be jit-compiled by the caller; rows arrive dense so
    the whole step lives on the MXU.
    """

    def __init__(self, channel: StageChannel, train_step: Callable,
                 params):
        self.channel = channel
        self.train_step = train_step
        self.params = params
        self.steps = 0

    def serve(self) -> int:
        """Consume staged micro-batches until the CPU section finishes.
        Returns the number of steps executed."""
        while True:
            msg = self.channel.recv()
            if msg is None or msg == _STOP:
                return self.steps
            ids, rows, dense_x, labels = msg
            self.params, loss, emb_grad = self.train_step(
                self.params, rows, dense_x, labels)
            self.channel.send((ids, np.asarray(emb_grad),
                               float(np.asarray(loss))))
            self.steps += 1
