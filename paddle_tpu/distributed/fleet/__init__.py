"""Fleet facade.

~ python/paddle/distributed/fleet/base/fleet_base.py:139 (fleet.init,
distributed_model:937, distributed_optimizer:880) + DistributedStrategy.
"""
from __future__ import annotations

from .distributed_strategy import DistributedStrategy  # noqa: F401
from .fleet_base import (  # noqa: F401
    Fleet, distributed_model, distributed_optimizer, get_hybrid_communicate_group,
    init, is_first_worker, worker_index, worker_num,
)
from . import meta_parallel  # noqa: F401
from . import heter  # noqa: F401
from .utils import recompute  # noqa: F401

from . import data_generator  # noqa: F401,E402
