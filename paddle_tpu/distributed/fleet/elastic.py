"""Elastic training manager.

~ python/paddle/distributed/fleet/elastic/manager.py:130 (ElasticManager:
etcd lease+watch on node membership, scale between --np min:max, relaunch
local trainers with rewritten rank envs). TPU-native substitution: the
membership registry is the TCPStore (heartbeat keys with timestamps); the
watcher detects dead/new peers and triggers pod relaunch through the
launch controller (launch/main.py elastic_level). No etcd dependency.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from ..store import TCPStore


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Heartbeat + membership watch over TCPStore."""

    def __init__(self, store: TCPStore, node_id: str, np_range=(1, 1),
                 heartbeat_interval: float = 2.0,
                 dead_after: float = 10.0):
        self.store = store
        self.node_id = node_id
        self.min_np, self.max_np = np_range
        self.interval = heartbeat_interval
        self.dead_after = dead_after
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._watchers: List[Callable[[List[str], List[str]], None]] = []
        self._last_members: List[str] = []

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        self.store.set(f"__hb__/{self.node_id}", str(time.time()))
        self._register_member()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def _register_member(self):
        # atomic slot claim via TCPStore.add — a read-modify-write on one
        # JSON membership key loses registrations when two nodes join
        # concurrently (the round-1 flaky TestElastic race)
        slot = self.store.add("__member_count__", 1) - 1
        self.store.set(f"__member_slot__/{slot}", self.node_id)

    def _members(self) -> List[str]:
        n = self.store.add("__member_count__", 0)
        seen = set()
        for i in range(int(n)):
            v = self.store.get(f"__member_slot__/{i}")
            if v:
                seen.add(v.decode())
        return sorted(seen)

    def _loop(self):
        while not self._stop.is_set():
            self.store.set(f"__hb__/{self.node_id}", str(time.time()))
            alive = self.alive_members()
            if self._last_members and alive != self._last_members:
                for w in self._watchers:
                    w(self._last_members, alive)
            self._last_members = alive
            self._stop.wait(self.interval)

    # -- membership ---------------------------------------------------------
    def alive_members(self) -> List[str]:
        members = self._members()
        now = time.time()
        alive = []
        for m in members:
            hb = self.store.get(f"__hb__/{m}")
            try:
                if hb and now - float(hb.decode()) < self.dead_after:
                    alive.append(m)
            except ValueError:
                pass
        return alive

    def watch(self, callback: Callable[[List[str], List[str]], None]):
        """callback(old_members, new_members) on membership change."""
        self._watchers.append(callback)

    # -- decisions ----------------------------------------------------------
    def pod_status(self) -> str:
        n = len(self.alive_members())
        if n < self.min_np:
            return ElasticStatus.HOLD
        if self._last_members and n != len(self._last_members):
            return ElasticStatus.RESTART
        return ElasticStatus.COMPLETED

    def should_scale(self) -> bool:
        n = len(self.alive_members())
        return self.min_np <= n <= self.max_np and (
            not self._last_members or n != len(self._last_members))
