"""Activation recompute (gradient checkpointing).

~ fleet/utils/recompute.py:331 (recompute(), EagerRecomputeFunction:65):
drop forward activations of a segment and recompute them in backward, with
RNG state restore so dropout masks match.

TPU-native implementation: ``jax.checkpoint`` (remat) composed with the
eager tape — the segment runs under jax.checkpoint inside the recorded vjp,
so XLA rematerializes inside the compiled backward. RNG determinism comes
from pre-drawing the generator offsets (keys are captured as closure
constants, so forward and recompute see identical randomness — the role of
the reference's RNG state stash/restore).
"""
from __future__ import annotations

import jax

from ....core.tensor import Tensor
from ....ops.dispatch import apply_op


def recompute(function, *args, **kwargs):
    """~ recompute.py:331. function: callable over Tensors."""
    preserve_rng_state = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)
    del preserve_rng_state, use_reentrant

    tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    others = {i: a for i, a in enumerate(args) if not isinstance(a, Tensor)}

    def fn(*tvals):
        def inner(*vals):
            merged = []
            it = iter(vals)
            for i in range(len(args)):
                merged.append(others[i] if i in others else Tensor(next(it)))
            out = function(*merged, **kwargs)
            if isinstance(out, Tensor):
                return out._value
            return tuple(o._value if isinstance(o, Tensor) else o
                         for o in out)
        return jax.checkpoint(inner)(*tvals)

    t_args = [args[i] for i in tensor_idx]
    return apply_op("recompute", fn, *t_args)


def recompute_sequential(ctx, functions, *args, **kwargs):
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    funcs = list(functions)
    seg_size = max(1, len(funcs) // segments)
    out = args
    for s in range(0, len(funcs), seg_size):
        chunk = funcs[s:s + seg_size]

        def run_chunk(*a, _chunk=chunk):
            o = a
            for f in _chunk:
                o = f(*o) if isinstance(o, tuple) else (f(o),)
            return o[0] if len(o) == 1 else o
        out = recompute(run_chunk, *(out if isinstance(out, tuple) else (out,)))
        if not isinstance(out, tuple):
            out = (out,)
    return out[0] if isinstance(out, tuple) and len(out) == 1 else out
