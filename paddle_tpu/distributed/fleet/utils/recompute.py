"""Activation recompute (gradient checkpointing).

~ fleet/utils/recompute.py:331 (recompute(), EagerRecomputeFunction:65):
drop forward activations of a segment and recompute them in backward, with
RNG state restore so dropout masks match.

Eager design: the forward runs under no_grad (no residuals retained — the
memory saving); the tape records ONE node whose pullback re-runs the
function with grad enabled and backpropagates through the fresh subgraph.
Parameter grads accumulate directly (the re-run touches the same Parameter
objects); input cotangents are returned to the outer graph. RNG (seed,
offset) state is snapshotted and restored so dropout masks match — the role
of the reference's CUDA RNG state stash.

Compiled paths use jax.checkpoint directly (see llama_train_step_factory);
this module is the eager/tape-level equivalent.
"""
from __future__ import annotations

import jax.numpy as jnp

from ....autograd import tape as _tape
from ....core import generator as _gen
from ....core.tensor import Tensor


def recompute(function, *args, preserve_rng_state: bool = True, **kwargs):
    """~ recompute.py:331."""
    kwargs.pop("use_reentrant", None)
    rng_state = _gen.get_rng_state() if preserve_rng_state else None

    with _tape.no_grad():
        outputs = function(*args, **kwargs)
    single = isinstance(outputs, Tensor)
    out_list = [outputs] if single else [o for o in outputs
                                         if isinstance(o, Tensor)]

    diff_inputs = [a for a in args
                   if isinstance(a, Tensor) and not a.stop_gradient]
    if not _tape.grad_enabled():
        return outputs

    def vjp_fn(cts):
        if not isinstance(cts, (tuple, list)):
            cts = (cts,)
        if preserve_rng_state:
            post_state = _gen.get_rng_state()
            _gen.set_rng_state(rng_state)
        # re-run with fresh, grad-tracked input copies
        replay_args = []
        replay_inputs = []
        for a in args:
            if isinstance(a, Tensor) and not a.stop_gradient:
                ra = Tensor(a._value, stop_gradient=False)
                replay_inputs.append(ra)
                replay_args.append(ra)
            else:
                replay_args.append(a)
        with _tape.enable_grad():
            re_out = function(*replay_args, **kwargs)
        if preserve_rng_state:
            _gen.set_rng_state(post_state)
        re_list = [re_out] if isinstance(re_out, Tensor) else \
            [o for o in re_out if isinstance(o, Tensor)]
        # backprop the cotangents through the replayed subgraph;
        # parameter grads accumulate as in normal backward
        _tape.backward(re_list, [Tensor(c) for c in cts])
        grads = []
        for ra in replay_inputs:
            g = ra._grad
            grads.append(g._value if g is not None
                         else jnp.zeros(ra.shape, ra._value.dtype))
        return tuple(grads)

    node = _tape.GradNode("recompute", vjp_fn, diff_inputs,
                          [(tuple(o.shape), o._value.dtype)
                           for o in out_list])
    for i, o in enumerate(out_list):
        o.stop_gradient = False
        o._grad_node = node
        o._output_index = i
    return outputs


def recompute_sequential(ctx, functions, *args, **kwargs):
    """~ incubate recompute_sequential — segment a Sequential-like list."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    funcs = list(functions)
    seg_size = max(1, len(funcs) // segments)
    out = args
    for s in range(0, len(funcs), seg_size):
        chunk = funcs[s:s + seg_size]

        def run_chunk(*a, _chunk=chunk):
            o = a
            for f in _chunk:
                o = f(*o) if isinstance(o, tuple) else (f(o),)
            return o[0] if len(o) == 1 else o
        out = recompute(run_chunk,
                        *(out if isinstance(out, tuple) else (out,)))
        if not isinstance(out, tuple):
            out = (out,)
    return out[0] if isinstance(out, tuple) and len(out) == 1 else out
