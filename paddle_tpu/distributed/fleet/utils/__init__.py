from . import fs, recompute  # noqa: F401
from .fs import FS, HDFSClient, LocalFS  # noqa: F401
from .recompute import recompute as recompute_fn  # noqa: F401
