from . import fs, recompute  # noqa: F401
from .fs import FS, HDFSClient, LocalFS  # noqa: F401
from .recompute import recompute as recompute_fn  # noqa: F401
from . import internal_storage  # noqa: F401,E402
from .internal_storage import (GradStorage,  # noqa: F401,E402
                               ParamStorage, TensorBucket,
                               fused_all_reduce)
from . import hybrid_parallel_inference  # noqa: F401,E402
from .hybrid_parallel_inference import (  # noqa: F401,E402
    HybridParallelInferenceHelper)
