"""Fused flat buffers for parameters/gradients.

~ fleet/meta_parallel/sharding/group_sharded_storage.py (ParamStorage /
GradStorage: one contiguous buffer per rank+dtype that many tensors view
into, so comm ops run once per bucket instead of once per tensor). The
TPU form packs with concatenate/split — XLA turns the pack-allreduce-
unpack into a single fused collective over the bucket.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import jax.numpy as jnp
import numpy as np


class TensorBucket:
    """One dtype-homogeneous bucket of tensors with a flat fused form."""

    def __init__(self, dtype):
        self.dtype = jnp.dtype(dtype)
        self._shapes: List[Tuple[int, ...]] = []
        self._sizes: List[int] = []
        self.tensors: List = []

    def add(self, value) -> int:
        """Register one array; returns its slot index."""
        self._shapes.append(tuple(value.shape))
        self._sizes.append(int(np.prod(value.shape)) if value.ndim else 1)
        self.tensors.append(value)
        return len(self.tensors) - 1

    @property
    def numel(self) -> int:
        return sum(self._sizes)

    def pack(self) -> jnp.ndarray:
        """Flatten all registered arrays into one contiguous buffer."""
        return jnp.concatenate(
            [jnp.ravel(t).astype(self.dtype) for t in self.tensors])

    def unpack(self, flat) -> List[jnp.ndarray]:
        """Split a fused buffer back into the registered shapes."""
        out = []
        off = 0
        for shape, size in zip(self._shapes, self._sizes):
            out.append(jnp.reshape(flat[off:off + size], shape))
            off += size
        return out


class GradStorage:
    """~ group_sharded_storage.py GradStorage: bucket gradients by dtype
    under a byte budget; comm runs per bucket."""

    def __init__(self, max_bucket_bytes: int = 25 * 1024 * 1024):
        self.max_bucket_bytes = max_bucket_bytes
        self.buckets: List[TensorBucket] = []
        # assignments[i] = (bucket_idx, slot_idx) for input i — recorded
        # here so callers can restore input order without id() tricks
        self.assignments: List[Tuple[int, int]] = []

    def build(self, grads: List) -> List[TensorBucket]:
        by_dtype: Dict = {}
        for g in grads:
            key = jnp.dtype(g.dtype)
            cur = by_dtype.get(key)
            nbytes = int(np.prod(g.shape)) * key.itemsize
            if cur is None or cur._bytes + nbytes > self.max_bucket_bytes:
                cur = TensorBucket(key)
                cur._bytes = 0
                by_dtype[key] = cur
                self.buckets.append(cur)
            slot = cur.add(g)
            cur._bytes += nbytes
            self.assignments.append((self.buckets.index(cur), slot))
        return self.buckets


ParamStorage = GradStorage  # same mechanics; kept for API parity


def fused_all_reduce(grads: List, all_reduce_fn,
                     max_bucket_bytes: int = 25 * 1024 * 1024) -> List:
    """All-reduce ``grads`` in fused dtype buckets
    (~ Reducer::FusedAllReduceSchedule, imperative/reducer.h:153).

    all_reduce_fn: flat_array -> flat_array (the collective).
    Returns the reduced grads in the original order.
    """
    storage = GradStorage(max_bucket_bytes)
    buckets = storage.build(grads)
    reduced_per_bucket = [b.unpack(all_reduce_fn(b.pack()))
                          for b in buckets]
    return [reduced_per_bucket[bi][ti] for bi, ti in storage.assignments]
