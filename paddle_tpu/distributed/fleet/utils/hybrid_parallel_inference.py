"""Hybrid-parallel inference helper.

~ fleet/utils/hybrid_parallel_inference.py HybridParallelInferenceHelper
(:23): the reference splits a static program into mp x pp ranks and
inserts comm ops. TPU-native: the model's layer stack is segmented into
``num_pp`` jitted stage programs streamed by the fleet-executor carrier
(the micro-batch pipelining the reference's SectionWorker does), while
``num_mp`` is carried by GSPMD sharding annotations inside each stage —
no program surgery needed; XLA inserts the tensor-parallel collectives.
"""
from __future__ import annotations

from typing import Optional


class HybridParallelInferenceHelper:
    """Segment-and-pipeline a Layer (or static Program pair) for
    inference. For eager Layers this wraps DistModel
    (distributed/fleet_executor.py); for static programs it compiles the
    captured DAG per stage."""

    def __init__(self, startup_program=None, main_program=None, num_mp=1,
                 num_pp=1, micro_batch_size=1, beam_size=1, init_comm=True,
                 role_maker=None, model=None):
        self.num_mp = num_mp
        self.num_pp = num_pp
        self.micro_batch_size = micro_batch_size
        self._main_program = main_program
        self._model = model
        self._dist_model = None
        if model is not None:
            from ...fleet_executor import DistModel, DistModelConfig
            # n_microbatches is resolved per batch in run() — the
            # reference's micro_batch_size is the SIZE of each micro, not
            # the count
            cfg = DistModelConfig(model=model, nranks=num_mp * num_pp,
                                  n_microbatches=1)
            self._dist_model = DistModel(cfg, n_stages=max(1, num_pp))

    def gen_infer_program(self, sync_in_while_lastpp2firstpp_var_names=None,
                          sync_in_while_var_names=None,
                          debug=False):
        """~ helper.gen_infer_program: prepare the staged executable. For
        the eager path the DistModel already segmented the stack; static
        programs compile lazily in the Executor."""
        return self._dist_model if self._dist_model is not None \
            else self._main_program

    def run(self, inputs, exe=None, feed=None, fetch_list=None):
        """Run pipelined inference: eager Layer path streams micro-batches
        through the carrier; static path delegates to the Executor."""
        if self._dist_model is not None:
            import math as _math
            batch = inputs.shape[0]
            self._dist_model._config.n_microbatches = max(
                1, _math.ceil(batch / max(1, self.micro_batch_size)))
            return self._dist_model.run(inputs)
        if exe is None:
            from ....static import Executor
            exe = Executor()
        return exe.run(self._main_program, feed=feed or inputs,
                       fetch_list=fetch_list)
