"""Filesystem clients (checkpointing substrate).

~ fleet/utils/fs.py (LocalFS + HDFSClient). HDFS has no place in this
environment; the interface is kept with LocalFS implementing it so
auto-checkpoint code paths are portable.
"""
from __future__ import annotations

import os
import shutil


class FS:
    def ls_dir(self, path):
        raise NotImplementedError

    def is_dir(self, path):
        raise NotImplementedError

    def is_file(self, path):
        raise NotImplementedError

    def is_exist(self, path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    def mv(self, src, dst, overwrite=False):
        raise NotImplementedError


class LocalFS(FS):
    """~ fs.py LocalFS."""

    def ls_dir(self, path):
        if not os.path.exists(path):
            return [], []
        dirs, files = [], []
        for e in os.listdir(path):
            (dirs if os.path.isdir(os.path.join(path, e)) else files).append(e)
        return dirs, files

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def is_exist(self, path):
        return os.path.exists(path)

    def upload(self, local_path, fs_path):
        self.mkdirs(os.path.dirname(fs_path))
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
        shutil.copy(fs_path, local_path)

    def mkdirs(self, path):
        if path:
            os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def mv(self, src, dst, overwrite=False):
        if overwrite and os.path.exists(dst):
            self.delete(dst)
        shutil.move(src, dst)

    def touch(self, path, exist_ok=True):
        self.mkdirs(os.path.dirname(path))
        open(path, "a").close()


class HDFSClient(FS):
    """Interface parity stub: raises with guidance (no HDFS in scope)."""

    def __init__(self, hadoop_home=None, configs=None):
        raise NotImplementedError(
            "HDFS is out of scope for the TPU build (SURVEY.md §7 "
            "non-goals); use LocalFS or orbax/tensorstore paths "
            "(gs:// works natively through tensorstore)")
