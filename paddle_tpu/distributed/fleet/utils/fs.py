"""Filesystem clients (checkpointing substrate).

~ fleet/utils/fs.py (LocalFS + HDFSClient). LocalFS implements the full
interface over the host filesystem. HDFSClient is a real client over the
`hadoop fs` CLI (the same transport the reference uses —
/root/reference/python/paddle/distributed/fleet/utils/fs.py:451 builds
`{hadoop_home}/bin/hadoop fs` command lines); it degrades with a clear
ExecuteError when the binary is absent, and tests exercise it with a fake
`hadoop` shim on PATH.
"""
from __future__ import annotations

import os
import shutil
import subprocess
import time


class FSFileExistsError(IOError):
    pass


class FSFileNotExistsError(IOError):
    pass


class ExecuteError(IOError):
    pass


class FSTimeOut(IOError):
    pass


class FS:
    def ls_dir(self, path):
        raise NotImplementedError

    def is_dir(self, path):
        raise NotImplementedError

    def is_file(self, path):
        raise NotImplementedError

    def is_exist(self, path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    def mv(self, src, dst, overwrite=False):
        raise NotImplementedError

    def touch(self, path, exist_ok=True):
        raise NotImplementedError

    def cat(self, path):
        raise NotImplementedError

    def need_upload_download(self):
        return False


class LocalFS(FS):
    """~ fs.py LocalFS."""

    def ls_dir(self, path):
        if not os.path.exists(path):
            return [], []
        dirs, files = [], []
        for e in os.listdir(path):
            (dirs if os.path.isdir(os.path.join(path, e)) else files).append(e)
        return dirs, files

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def is_exist(self, path):
        return os.path.exists(path)

    def upload(self, local_path, fs_path):
        self.mkdirs(os.path.dirname(fs_path))
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
        shutil.copy(fs_path, local_path)

    def mkdirs(self, path):
        if path:
            os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def mv(self, src, dst, overwrite=False):
        if not overwrite and os.path.exists(dst):
            raise FSFileExistsError(dst)
        if overwrite and os.path.exists(dst):
            self.delete(dst)
        shutil.move(src, dst)

    def touch(self, path, exist_ok=True):
        if os.path.exists(path):
            if exist_ok:
                return
            raise FSFileExistsError(path)
        self.mkdirs(os.path.dirname(path))
        open(path, "a").close()

    def cat(self, path):
        with open(path, "rb") as f:
            return f.read().decode("utf-8", "replace")

    def list_dirs(self, path):
        dirs, _ = self.ls_dir(path)
        return dirs


class HDFSClient(FS):
    """HDFS client over the `hadoop fs` CLI.

    ~ reference fs.py HDFSClient (:393): command lines match the
    reference's (`-ls`, `-test -d/-e/-z`, `-put`, `-get`, `-mkdir -p`,
    `-mv`, `-rm -r`, `-touchz`, `-cat`), with bounded retries. The
    `hadoop` executable comes from hadoop_home/bin, or PATH when
    hadoop_home is None — which is how tests inject a fake shim.
    """

    def __init__(self, hadoop_home=None, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        if hadoop_home:
            self._base = [os.path.join(hadoop_home, "bin", "hadoop"), "fs"]
        else:
            self._base = ["hadoop", "fs"]
        for k, v in (configs or {}).items():
            self._base += ["-D", f"{k}={v}"]
        self._time_out = time_out / 1000.0
        self._sleep_inter = sleep_inter / 1000.0

    # -- low-level --------------------------------------------------------
    def _run(self, *args, retries=3, check=True):
        last = None
        for attempt in range(retries):
            try:
                r = subprocess.run(
                    [*self._base, *args], capture_output=True, text=True,
                    timeout=self._time_out)
            except FileNotFoundError as e:
                raise ExecuteError(
                    f"hadoop binary not found ({self._base[0]}); install "
                    "hadoop or pass hadoop_home") from e
            except subprocess.TimeoutExpired as e:
                raise FSTimeOut(f"hadoop fs {' '.join(args)}") from e
            if r.returncode == 0 or not check:
                return r
            last = r
            time.sleep(self._sleep_inter)
        raise ExecuteError(
            f"hadoop fs {' '.join(args)} failed rc={last.returncode}: "
            f"{last.stderr.strip()[-500:]}")

    def _test(self, flag, path):
        return self._run("-test", flag, path, check=False).returncode == 0

    # -- FS interface -----------------------------------------------------
    def ls_dir(self, path):
        if not self.is_exist(path):
            return [], []
        out = self._run("-ls", path).stdout
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue  # "Found N items" header / malformed
            name = parts[-1].rstrip("/").rsplit("/", 1)[-1]
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_dir(self, path):
        return self._test("-d", path)

    def is_file(self, path):
        return self.is_exist(path) and not self.is_dir(path)

    def is_exist(self, path):
        return self._test("-e", path)

    def upload(self, local_path, fs_path):
        if not os.path.exists(local_path):
            raise FSFileNotExistsError(local_path)
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        if not self.is_exist(fs_path):
            raise FSFileNotExistsError(fs_path)
        os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
        self._run("-get", fs_path, local_path)

    def mkdirs(self, path):
        if not self.is_exist(path):
            self._run("-mkdir", "-p", path)

    def delete(self, path):
        if self.is_exist(path):
            self._run("-rm", "-r", path)

    def mv(self, src, dst, overwrite=False):
        if not overwrite and self.is_exist(dst):
            raise FSFileExistsError(dst)
        if overwrite and self.is_exist(dst):
            self.delete(dst)
        self._run("-mv", src, dst)

    def touch(self, path, exist_ok=True):
        if self.is_exist(path):
            if exist_ok:
                return
            raise FSFileExistsError(path)
        self._run("-touchz", path)

    def cat(self, path):
        if not self.is_exist(path):
            return ""
        return self._run("-cat", path).stdout

    def list_dirs(self, path):
        dirs, _ = self.ls_dir(path)
        return dirs

    def need_upload_download(self):
        return True
