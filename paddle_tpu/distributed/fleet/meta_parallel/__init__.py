"""meta_parallel: parallel wrappers + parallel layers.

~ python/paddle/distributed/fleet/meta_parallel/.
"""
from .parallel_layers import (  # noqa: F401
    ColumnParallelLinear, LayerDesc, ParallelCrossEntropy, PipelineLayer,
    RNGStatesTracker, RowParallelLinear, SegmentLayers, SharedLayerDesc,
    VocabParallelEmbedding, get_rng_state_tracker, model_parallel_random_seed,
)
from .tensor_parallel import TensorParallel  # noqa: F401
from .pipeline_parallel import PipelineParallel  # noqa: F401
from .hybrid_parallel_optimizer import HybridParallelOptimizer  # noqa: F401
from .sharding_parallel import ShardingParallel  # noqa: F401
