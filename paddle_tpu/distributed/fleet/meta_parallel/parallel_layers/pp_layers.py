"""Pipeline layer description + segmentation.

~ fleet/meta_parallel/parallel_layers/pp_layers.py: LayerDesc:58,
SharedLayerDesc:76, SegmentLayers:90, PipelineLayer:159
(_segment_network:314, shared-weight handling :295).

The description/segmentation API is preserved verbatim; execution differs:
on TPU the stages run either (a) eagerly on one device (this class builds
only the local stage's layers when an hcg with pp>1 is installed), or
(b) compiled, where paddle_tpu.parallel.pipeline stacks homogeneous stage
params and scans with ppermute transfers over the 'pipe' mesh axis.
"""
from __future__ import annotations

import math
import re
from functools import partial
from typing import List

from .....nn.layer.layers import Layer, LayerList, Sequential
from .... import topology as _topo


class LayerDesc:
    """~ pp_layers.py:58."""

    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_cls, Layer):
            raise TypeError("layer_cls must be a paddle_tpu.nn.Layer subclass")

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """~ pp_layers.py:76 — layers shared across stages (tied embeddings)."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr
                 ="weight", *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """~ pp_layers.py:90 — split N layer descs into num_parts stages."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self._layers_desc = layers_desc
        self.method = method
        self.num_parts = num_parts
        self.num_items = len(layers_desc)
        if self.num_items < self.num_parts:
            raise ValueError("layer number should be greater than num_parts")

    def do_segment(self) -> List[int]:
        if self.method == "uniform":
            return self.uniform(self.num_items, self.num_parts)
        if self.method.startswith("layer:"):
            # segment by named layer occurrences (e.g. "layer:DecoderLayer")
            name = self.method.split(":", 1)[1]
            weights = [0] * len(self._layers_desc)
            for i, d in enumerate(self._layers_desc):
                cls = d.layer_cls if isinstance(d, LayerDesc) else type(d)
                if re.search(name, cls.__name__):
                    weights[i] = 1
            total = sum(weights)
            if total % self.num_parts != 0:
                raise ValueError(
                    f"{total} '{name}' layers not divisible into "
                    f"{self.num_parts} stages")
            per = total // self.num_parts
            result = [0]
            seen = 0
            for i, w in enumerate(weights):
                seen += w
                if seen == per and len(result) < self.num_parts:
                    result.append(i + 1)
                    seen = 0
            result.append(len(weights))
            return result
        raise ValueError(f"unknown segment method {self.method}")

    @staticmethod
    def uniform(num_items, num_parts) -> List[int]:
        result = [0] * (num_parts + 1)
        part_size = math.floor(num_items / num_parts)
        extra = num_items % num_parts
        for i in range(1, num_parts + 1):
            result[i] = result[i - 1] + part_size + (1 if i <= extra else 0)
        return result


class PipelineLayer(Layer):
    """~ pp_layers.py:159."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._layers_desc = list(layers)
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        self._topo = topology
        hcg = _topo.get_hybrid_communicate_group()
        if num_stages is None and hcg is not None:
            num_stages = hcg.get_pipe_parallel_world_size()
        self._num_stages = num_stages or 1
        self._stage_id = hcg.get_stage_id() if hcg else 0

        self.segment_parts = SegmentLayers(
            self._layers_desc, self._num_stages, seg_method).do_segment()
        self._start = self.segment_parts[self._stage_id]
        self._end = self.segment_parts[self._stage_id + 1]

        self.shared_layers = {}
        self.shared_weight_attrs = {}
        self._build_layer()

    # -- construction -------------------------------------------------------
    def _build_layer(self):
        run_funcs = []
        local = []
        # deterministic per-layer-index RNG: each stage builds ONLY its
        # segment, so without this the generator state (and thus param
        # init) would depend on which stage builds — breaking cross-stage
        # parity with the single-process model (the reference gets this
        # from per-rank seed control in fleet.meta_parallel random.py)
        from .....core.generator import default_generator
        _gen = default_generator()
        _seed, _off0 = _gen.get_state()
        _stride = 100003
        self._init_rng = (_seed, _off0)
        for i, d in enumerate(self._layers_desc):
            _gen.set_state((_seed, _off0 + i * _stride))
            in_local = self._start <= i < self._end
            if isinstance(d, SharedLayerDesc):
                # build shared layers everywhere they appear (weights tied)
                if d.layer_name not in self.shared_layers:
                    self.shared_layers[d.layer_name] = d.build_layer()
                    self.shared_weight_attrs[d.layer_name] = \
                        d.shared_weight_attr
                    self.add_sublayer(f"shared_{d.layer_name}",
                                      self.shared_layers[d.layer_name])
                if in_local:
                    layer = self.shared_layers[d.layer_name]
                    if d.forward_func is None:
                        run_funcs.append(layer)
                    else:
                        run_funcs.append(partial(d.forward_func, layer))
            elif isinstance(d, LayerDesc):
                if in_local:
                    layer = d.build_layer()
                    local.append(layer)
                    run_funcs.append(layer)
            else:  # plain Layer or callable
                if in_local:
                    if isinstance(d, Layer):
                        local.append(d)
                    run_funcs.append(d)
        _gen.set_state((_seed, _off0 + len(self._layers_desc) * _stride))
        self.run_function = run_funcs
        self._local_layers = LayerList(
            [l for l in local if isinstance(l, Layer)])

    def get_stage_from_index(self, layer_idx) -> int:
        for stage in range(self._num_stages):
            if (self.segment_parts[stage] <= layer_idx
                    < self.segment_parts[stage + 1]):
                return stage
        raise ValueError("layer_idx out of range")

    @property
    def parameters_desc(self):
        return self._layers_desc

    def get_num_stages(self):
        return self._num_stages

    def allreduce_shared_weight_gradients(self):
        """~ pp_layers.py:295 — tied-weight grad sync across stages. In
        compiled GSPMD execution shared weights are one logical tensor, so
        grads are already combined; eager multi-process mode syncs here."""
        from .... import collective as C
        for name, layer in self.shared_layers.items():
            attr = self.shared_weight_attrs[name]
            p = getattr(layer, attr, None)
            if p is not None and p._grad is not None:
                C.all_reduce(p._grad)

    def forward(self, input, chunk_id=None):
        out = input
        for fn in self.run_function:
            if isinstance(out, tuple):
                out = fn(*out)
            else:
                out = fn(out)
        return out

    def forward_full(self, input):
        """Run ALL stages (single-program GSPMD mode). Reuses the local
        segment's already-built (trained!) layers; only non-local descs are
        instantiated — and those with the same per-index deterministic RNG
        as _build_layer so init matches the staged build."""
        out = input
        built = getattr(self, "_full_layers", None)
        if built is None:
            from .....core.generator import default_generator
            _gen = default_generator()
            _seed, _off0 = _gen.get_state()
            _stride = 100003
            built = []
            li = 0
            for i, d in enumerate(self._layers_desc):
                in_local = self._start <= i < self._end
                if isinstance(d, SharedLayerDesc):
                    layer = self.shared_layers[d.layer_name]
                    built.append(layer if d.forward_func is None
                                 else partial(d.forward_func, layer))
                    if in_local:
                        li += 1
                elif in_local:
                    built.append(self.run_function[li])
                    li += 1
                elif isinstance(d, LayerDesc):
                    bseed, boff = getattr(self, "_init_rng", (_seed, 0))
                    _gen.set_state((bseed, boff + i * _stride))
                    built.append(d.build_layer())
                else:
                    built.append(d)
            _gen.set_state((_seed, _off0))
            self._full_layers = built
        for fn in built:
            out = fn(*out) if isinstance(out, tuple) else fn(out)
        return out
