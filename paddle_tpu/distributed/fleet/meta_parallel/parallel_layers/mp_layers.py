"""Tensor (model) parallel layers.

~ fleet/meta_parallel/parallel_layers/mp_layers.py:
VocabParallelEmbedding:30, ColumnParallelLinear:97, RowParallelLinear:170,
ParallelCrossEntropy:249.

TPU-native (GSPMD) design: layers hold FULL logical weights annotated with
PartitionSpecs on the "model" mesh axis. Under pjit/shard_map the annotation
shards the weight and XLA inserts the same collectives the reference codes
by hand (c_identity = no-op, mp allreduce = psum over 'model', c_concat =
all_gather). Eagerly on one device they are ordinary layers, which also
makes single-chip correctness tests trivial.  The reference's manual
rank-slicing (per-rank weight shards + explicit c_ops) would fight XLA's
partitioner — annotation is the idiomatic TPU form of the same math.
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

from .....nn import functional as F
from .....nn import initializer as init
from .....nn.layer.layers import Layer
from .... import topology as _topo


def _mp_world():
    hcg = _topo.get_hybrid_communicate_group()
    return hcg.get_model_parallel_world_size() if hcg else 1


class VocabParallelEmbedding(Layer):
    """~ mp_layers.py:30 — embedding table sharded over vocab dim."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=init.XavierNormal())
        # vocab rows sharded across the model axis
        self.weight.sharding_spec = P("model", None)

    def forward(self, x):
        return F.embedding(x, self.weight)


class ColumnParallelLinear(Layer):
    """~ mp_layers.py:97 — weight cols sharded; gather_output optional."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=init.XavierNormal())
        self.weight.sharding_spec = P(None, "model")
        if has_bias is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias.sharding_spec = P("model")

    def forward(self, x):
        # under pjit: x replicated over 'model', out sharded on last dim;
        # gather_output=True -> all_gather inserted by the partitioner when
        # the consumer needs it replicated. No manual c_identity needed.
        return F.linear(x, self.weight, self.bias)


class RowParallelLinear(Layer):
    """~ mp_layers.py:170 — weight rows sharded; inputs split."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=init.XavierNormal())
        self.weight.sharding_spec = P("model", None)
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias.sharding_spec = None  # replicated
        else:
            self.bias = None

    def forward(self, x):
        # contraction over the sharded dim -> XLA inserts psum over 'model'
        # (the hand-written mp_allreduce_sum of the reference)
        return F.linear(x, self.weight, self.bias)


class ParallelCrossEntropy(Layer):
    """~ mp_layers.py:249 (c_softmax_with_cross_entropy).

    With logits sharded over classes on 'model', XLA partitions the
    log-softmax reduction into the max/sum psums the reference implements in
    c_softmax_with_cross_entropy_op.cu.
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        loss = F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
        from .....ops.manipulation import unsqueeze
        return unsqueeze(loss, -1)
