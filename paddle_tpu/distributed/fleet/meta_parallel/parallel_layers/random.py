"""Model-parallel RNG state tracker.

~ fleet/meta_parallel/parallel_layers/random.py:32 (RNGStatesTracker,
model_parallel_random_seed:86): dropout inside TP layers must differ per mp
rank (local dropout) while plain dropout stays identical across ranks.
Implemented over (seed, offset) Generators.
"""
from __future__ import annotations

from contextlib import contextmanager

from .....core.generator import Generator

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name: str, seed: int):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.seeds_.add(seed)
        self.states_[name] = Generator(seed)

    def get_states_tracker(self):
        return {n: g.get_state() for n, g in self.states_.items()}

    def set_states_tracker(self, states):
        for n, s in states.items():
            self.states_.setdefault(n, Generator(0)).set_state(s)

    @contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        from .....core import generator as _gen
        prev = _gen._default_generator
        _gen._default_generator = self.states_[name]
        try:
            yield
        finally:
            _gen._default_generator = prev


RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    """~ random.py:86 — distinct seed per mp rank, same across dp ranks."""
    from ..... import topology as _topo
    import random as _pyrandom
    hcg = _topo.get_hybrid_communicate_group()
    rank = hcg.get_model_parallel_rank() if hcg else 0
    if seed:
        global_seed = seed
        local_seed = seed * 1024 + rank * 100
    else:
        global_seed = _pyrandom.randint(0, 655350)
        local_seed = _pyrandom.randint(rank * 10000, (rank + 1) * 10000 - 1)
    RNG_STATE_TRACKER.reset()
    RNG_STATE_TRACKER.add(MODEL_PARALLEL_RNG, local_seed)
    from .....core import generator as _gen
    _gen.seed(global_seed)
