from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)
from .pp_layers import (  # noqa: F401
    LayerDesc, PipelineLayer, SegmentLayers, SharedLayerDesc,
)
from .random import (  # noqa: F401
    RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed,
)
