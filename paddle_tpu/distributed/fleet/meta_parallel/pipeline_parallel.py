"""PipelineParallel wrapper + 1F1B schedule.

~ fleet/meta_parallel/pipeline_parallel.py: PipelineParallel:31,
forward_backward_pipeline:81 (1F1B startup/steady/cooldown :97-146),
train_batch:153; p2p protocol pp_utils/p2p_communication.py.

TPU execution modes:
  * single-program (default when the whole mesh is visible): micro-batches
    run sequentially over the FULL layer stack with grad accumulation —
    semantically identical to 1F1B (same loss/grads); stage overlap comes
    from the compiled pipeline in paddle_tpu.parallel.pipeline (shard_map +
    ppermute over the 'pipe' axis) used on the jit path.
  * multi-process: eager p2p via host collectives (correctness path).
"""
from __future__ import annotations

import jax.numpy as jnp

from ....core.tensor import Tensor
from ....nn.layer.layers import Layer
from .parallel_layers.pp_layers import PipelineLayer


class PipelineParallel(Layer):
    def __init__(self, layers, hcg, strategy):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = strategy.pipeline_configs
        self.micro_batch_size = cfg.micro_batch_size
        self.accumulate_steps = cfg.accumulate_steps
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self.stage_id = hcg.get_stage_id()
        self.total_loss = None

    def forward(self, *inputs, **kwargs):
        return self._layers.forward_full(*inputs, **kwargs)

    def _split_micro(self, data):
        if isinstance(data, (tuple, list)):
            micro = [self._split_micro(d) for d in data]
            return list(zip(*micro))
        n = self.accumulate_steps
        B = data.shape[0]
        mb = B // n if B >= n else B
        return [data[i * mb:(i + 1) * mb] for i in range(n)]

    def forward_backward_pipeline(self, data, scaler=None):
        """1F1B-equivalent accumulation (~ pipeline_parallel.py:81)."""
        inputs, labels = data
        micro_inputs = self._split_micro(inputs)
        micro_labels = self._split_micro(labels)
        total = None
        for x, y in zip(micro_inputs, micro_labels):
            out = self._layers.forward_full(x)
            if self._layers._loss_fn is not None:
                loss = self._layers._loss_fn(out, y)
            else:
                loss = out
            scaled = loss * (1.0 / self.accumulate_steps)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total = loss.detach() if total is None \
                else total + loss.detach()
        self._layers.allreduce_shared_weight_gradients()
        self.total_loss = total * (1.0 / self.accumulate_steps)
        return self.total_loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """~ pipeline_parallel.py train_batch:153."""
        self.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self.eval()
        inputs, labels = data
        from ....autograd import no_grad
        with no_grad():
            out = self._layers.forward_full(inputs)
            if compute_loss and self._layers._loss_fn is not None:
                return self._layers._loss_fn(out, labels)
        return out

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, st, **kw):
        return self._layers.set_state_dict(st, **kw)
