"""PipelineParallel wrapper + 1F1B schedule.

~ fleet/meta_parallel/pipeline_parallel.py: PipelineParallel:31,
forward_backward_pipeline:81 (1F1B startup/steady/cooldown :97-146),
train_batch:153; p2p protocol pp_utils/p2p_communication.py:217.

TPU execution modes:
  * single-process (whole mesh visible): micro-batches run sequentially
    over the FULL layer stack with grad accumulation — same loss/grads;
    stage overlap comes from the compiled pipeline in
    paddle_tpu.parallel.pipeline (shard_map + ppermute over 'pipe').
  * multi-process (world == pp stages): REAL pipeline — each rank runs
    only its PipelineLayer segment; activations/grads move between stage
    processes over TCPStore p2p in 1F1B order (warmup fwds = stages -
    stage_id - 1, steady 1F1B, cooldown bwds).
"""
from __future__ import annotations

import os
from collections import deque

import jax.numpy as jnp

from ....core.tensor import Tensor
from ....nn.layer.layers import Layer
from ... import env as _env
from .parallel_layers.pp_layers import PipelineLayer


class PipelineParallel(Layer):
    def __init__(self, layers, hcg, strategy):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = strategy.pipeline_configs
        self.micro_batch_size = cfg.micro_batch_size
        self.accumulate_steps = cfg.accumulate_steps
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self.stage_id = hcg.get_stage_id()
        self.total_loss = None
        self._p2p = None

    # -- multi-process plumbing --------------------------------------------
    def _multiproc(self) -> bool:
        return (_env.get_world_size() > 1 and self.num_stages > 1
                and os.environ.get("PADDLE_MASTER") is not None)

    def _get_p2p(self):
        if self._p2p is None:
            from ....distributed.store import TCPStore
            from .pp_utils import P2PCommunicator
            host, port = os.environ["PADDLE_MASTER"].split(":")
            store = TCPStore(host, int(port) + 57,
                             is_master=(_env.get_rank() == 0),
                             world_size=_env.get_world_size())
            dp = self._hcg.get_data_parallel_rank() \
                if hasattr(self._hcg, "get_data_parallel_rank") else 0
            self._p2p = P2PCommunicator(
                store, self.stage_id, prefix=f"__pp_p2p__/dp{dp}")
        return self._p2p

    def forward(self, *inputs, **kwargs):
        return self._layers.forward_full(*inputs, **kwargs)

    def _split_micro(self, data):
        if isinstance(data, (tuple, list)):
            micro = [self._split_micro(d) for d in data]
            return list(zip(*micro))
        n = self.accumulate_steps
        B = data.shape[0]
        mb = B // n if B >= n else B
        return [data[i * mb:(i + 1) * mb] for i in range(n)]

    def forward_backward_pipeline(self, data, scaler=None):
        """~ pipeline_parallel.py:81."""
        inputs, labels = data
        micro_inputs = self._split_micro(inputs)
        micro_labels = self._split_micro(labels)
        if self._multiproc():
            return self._pipeline_1f1b(micro_inputs, micro_labels, scaler)
        total = None
        for x, y in zip(micro_inputs, micro_labels):
            out = self._layers.forward_full(x)
            if self._layers._loss_fn is not None:
                loss = self._layers._loss_fn(out, y)
            else:
                loss = out
            scaled = loss * (1.0 / self.accumulate_steps)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total = loss.detach() if total is None \
                else total + loss.detach()
        self._layers.allreduce_shared_weight_gradients()
        self.total_loss = total * (1.0 / self.accumulate_steps)
        return self.total_loss

    # -- real multi-process 1F1B -------------------------------------------
    def _pipeline_1f1b(self, micro_inputs, micro_labels, scaler):
        """1F1B over stage processes (~ forward_backward_pipeline:97-146:
        startup forwards, steady one-forward-one-backward, cooldown
        backwards). Each rank runs ONLY its segment; boundary tensors move
        via TCPStore p2p."""
        p2p = self._get_p2p()
        first = self.stage_id == 0
        last = self.stage_id == self.num_stages - 1
        n = len(micro_inputs)
        inflight = deque()  # (x_leaf|None, out|None, loss|None) FIFO
        total = 0.0

        def fwd(i):
            if first:
                x = micro_inputs[i]
                if not isinstance(x, Tensor):
                    x = Tensor(jnp.asarray(x))
            else:
                x = Tensor(jnp.asarray(p2p.recv(self.stage_id - 1)),
                           stop_gradient=False)
            out = self._layers.forward(x)
            loss = None
            if last:
                y = micro_labels[i]
                loss = self._layers._loss_fn(out, y) \
                    if self._layers._loss_fn is not None else out
                loss = loss * (1.0 / n)
            else:
                p2p.send(out.numpy(), self.stage_id + 1)
            inflight.append((x, out, loss))

        def bwd():
            nonlocal total
            x, out, loss = inflight.popleft()
            if last:
                (scaler.scale(loss) if scaler is not None
                 else loss).backward()
                total += float(loss.numpy()) * n
            else:
                g = p2p.recv(self.stage_id + 1, tag="grad")
                from ....autograd import backward as tape_backward
                tape_backward(out, Tensor(jnp.asarray(g)))
            if not first:
                p2p.send(x.grad.numpy(), self.stage_id - 1, tag="grad")

        warmup = min(self.num_stages - self.stage_id - 1, n)
        for i in range(warmup):                   # startup
            fwd(i)
        for i in range(warmup, n):                # steady 1F1B
            fwd(i)
            bwd()
        while inflight:                           # cooldown
            bwd()

        self._layers.allreduce_shared_weight_gradients()
        mean_loss = p2p.bcast_scalar(
            total / n if last else None, self.num_stages - 1)
        self.total_loss = Tensor(jnp.asarray(mean_loss, jnp.float32))
        return self.total_loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """~ pipeline_parallel.py train_batch:153."""
        self.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self.eval()
        inputs, labels = data
        from ....autograd import no_grad
        with no_grad():
            out = self._layers.forward_full(inputs)
            if compute_loss and self._layers._loss_fn is not None:
                return self._layers._loss_fn(out, labels)
        return out

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, st, **kw):
        return self._layers.set_state_dict(st, **kw)
