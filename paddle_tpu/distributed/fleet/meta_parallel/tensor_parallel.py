"""TensorParallel model wrapper.

~ fleet/meta_parallel/tensor_parallel.py:25 — in the reference this
broadcasts mp params inside the mp group at wrap time. With GSPMD the wrap
step instead validates sharding annotations; param consistency across ranks
comes from identical seeding (model_parallel_random_seed) + the compiled
path treating annotated params as one logical tensor.
"""
from __future__ import annotations

from ....nn.layer.layers import Layer


class TensorParallel(Layer):
    def __init__(self, layers: Layer, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, st, **kw):
        return self._layers.set_state_dict(st, **kw)
