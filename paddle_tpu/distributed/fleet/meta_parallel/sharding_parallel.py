"""ShardingParallel wrapper (~ fleet/meta_parallel/sharding_parallel.py).

GSPMD carries ZeRO semantics via optimizer-state sharding annotations (see
paddle_tpu.distributed.sharding); the wrapper is a thin marker layer kept
for wrapper-selection parity.
"""
from __future__ import annotations

from ....nn.layer.layers import Layer


class ShardingParallel(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, st, **kw):
        return self._layers.set_state_dict(st, **kw)
