"""HybridParallelOptimizer + grad clip across groups.

~ fleet/meta_parallel/dygraph_optimizer/hybrid_parallel_optimizer.py:45,170:
wraps the inner optimizer; global-norm grad clip must reduce the squared
norm across mp/pp/sharding groups (HybridParallelClipGrad:45). In compiled
GSPMD execution norms over annotated params are already global; the eager
multi-process path all-reduces the partial norms here.
"""
from __future__ import annotations

import jax.numpy as jnp

from ....nn import ClipGradByGlobalNorm
from ... import collective as C


class HybridParallelClipGrad:
    def __init__(self, clip, hcg):
        self._clip = clip
        self._hcg = hcg

    def __call__(self, params, grads):
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads)
        # sum partial norms across model-parallel group (eager multi-proc)
        from ....core.tensor import Tensor
        t = Tensor(sq)
        if self._hcg.get_model_parallel_world_size() > 1:
            C.all_reduce(t, group=self._hcg.get_model_parallel_group())
        if self._hcg.get_pipe_parallel_world_size() > 1:
            C.all_reduce(t, group=self._hcg.get_pipe_parallel_group())
        gn = jnp.sqrt(t._value)
        scale = jnp.minimum(1.0, self._clip.clip_norm / jnp.maximum(gn, 1e-12))
        return [(g * scale).astype(g.dtype) for g in grads]


class HybridParallelOptimizer:
    """~ hybrid_parallel_optimizer.py:170."""

    def __init__(self, optimizer, hcg, strategy):
        self._inner = optimizer
        self._hcg = hcg
        self._strategy = strategy
        clip = optimizer._grad_clip
        if isinstance(clip, ClipGradByGlobalNorm):
            self._hybrid_clip = HybridParallelClipGrad(clip, hcg)
            optimizer._grad_clip = None
        else:
            self._hybrid_clip = None

    def _sync_dp_grads(self):
        dp_group = self._hcg.get_data_parallel_group()
        if dp_group.nranks > 1:
            for p in self._inner._parameters:
                if p._grad is not None:
                    C.all_reduce(p._grad, group=dp_group)
                    p._grad._value = p._grad._value / dp_group.nranks

    def step(self):
        self._sync_dp_grads()
        if self._hybrid_clip is not None:
            params = [p for p in self._inner._parameters
                      if p.trainable and p._grad is not None]
            grads = [p._grad._value for p in params]
            clipped = self._hybrid_clip(params, grads)
            from ....core.tensor import Tensor
            for p, g in zip(params, clipped):
                p._grad = Tensor(g)
        self._inner.step()

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()

    def clear_grad(self):
        self._inner.clear_grad()

    clear_gradients = clear_grad

    def __getattr__(self, name):
        return getattr(self._inner, name)
