from .p2p_communication import P2PCommunicator  # noqa: F401
