"""Point-to-point activation/grad transport between pipeline stages.

~ reference fleet/meta_parallel/pp_utils/p2p_communication.py
(SendRecvMeta:39 — dtype/shape metadata protocol — and _p2p_helper:217,
batched isend/irecv between pipe stages). TPU-native difference: the
compiled pipeline (paddle_tpu.parallel.pipeline) moves activations with
ppermute over the 'pipe' mesh axis inside one XLA program; THIS module is
the eager multi-process correctness path, carrying tensors out-of-band
through the TCPStore rendezvous (true point-to-point — no global
collective alignment needed between stages running different schedules).
"""
from __future__ import annotations

import struct
from typing import Dict, Optional, Tuple

import numpy as np

_DTYPES = {
    0: np.float32, 1: np.float64, 2: np.float16, 3: np.int32,
    4: np.int64, 5: np.uint8, 6: np.bool_,
}
_DTYPE_IDS = {np.dtype(v): k for k, v in _DTYPES.items()}


def _pack(arr: np.ndarray) -> bytes:
    """SendRecvMeta analog: [dtype u8][ndim u8][dims i64...] + raw bytes."""
    arr = np.ascontiguousarray(arr)
    head = struct.pack("<BB", _DTYPE_IDS[arr.dtype], arr.ndim)
    head += struct.pack(f"<{arr.ndim}q", *arr.shape)
    return head + arr.tobytes()


def _unpack(buf: bytes) -> np.ndarray:
    dt_id, ndim = struct.unpack_from("<BB", buf, 0)
    shape = struct.unpack_from(f"<{ndim}q", buf, 2)
    off = 2 + 8 * ndim
    return np.frombuffer(buf, dtype=_DTYPES[dt_id],
                         offset=off).reshape(shape).copy()


class P2PCommunicator:
    """Sequenced p2p channels keyed (src_stage -> dst_stage, tag)."""

    def __init__(self, store, stage_id: int, prefix: str = "__pp_p2p__"):
        self._store = store
        self.stage_id = stage_id
        self._prefix = prefix
        self._send_seq: Dict[Tuple[int, str], int] = {}
        self._recv_seq: Dict[Tuple[int, str], int] = {}

    def _key(self, src: int, dst: int, tag: str, seq: int) -> str:
        return f"{self._prefix}/{src}->{dst}/{tag}/{seq}"

    def send(self, arr, dst_stage: int, tag: str = "act") -> None:
        k = (dst_stage, tag)
        seq = self._send_seq.get(k, 0)
        self._send_seq[k] = seq + 1
        self._store.set(self._key(self.stage_id, dst_stage, tag, seq),
                        _pack(np.asarray(arr)))

    def recv(self, src_stage: int, tag: str = "act") -> np.ndarray:
        k = (src_stage, tag)
        seq = self._recv_seq.get(k, 0)
        self._recv_seq[k] = seq + 1
        key = self._key(src_stage, self.stage_id, tag, seq)
        buf = self._store.wait(key)
        self._store.delete_key(key)
        return _unpack(buf)

    # -- scalar broadcast (the _broadcast_final_loss analog) ---------------
    def bcast_scalar(self, value: Optional[float], src_stage: int,
                     tag: str = "loss") -> float:
        k = (src_stage, tag)
        seq = self._send_seq.get(("__bc__", tag), 0)
        self._send_seq[("__bc__", tag)] = seq + 1
        key = f"{self._prefix}/bcast/{src_stage}/{tag}/{seq}"
        if self.stage_id == src_stage:
            self._store.set(key, struct.pack("<d", float(value)))
            return float(value)
        buf = self._store.wait(key)
        return struct.unpack("<d", buf)[0]
