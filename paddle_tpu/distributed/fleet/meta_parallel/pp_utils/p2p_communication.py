"""Point-to-point activation/grad transport between pipeline stages.

~ reference fleet/meta_parallel/pp_utils/p2p_communication.py
(SendRecvMeta:39 — dtype/shape metadata protocol — and _p2p_helper:217,
batched isend/irecv between pipe stages). TPU-native difference: the
compiled pipeline (paddle_tpu.parallel.pipeline) moves activations with
ppermute over the 'pipe' mesh axis inside one XLA program; THIS module is
the eager multi-process path.

Transport: persistent DIRECT rank-to-rank sockets — each stage runs one
listener; a (src -> dst) direction gets one connection, established
lazily and kept for the whole run, so stage traffic never funnels
through the rendezvous server. The TCPStore is used ONLY to exchange
listener addresses (and for the scalar loss broadcast, which is
rendezvous-shaped anyway). Frames are [tag][seq][payload]; per-connection
TCP ordering makes the per-(src, tag) streams FIFO, the property the
1F1B schedule relies on. (The round-3 implementation relayed every
tensor through the TCPStore master as KV pairs — correct, but it
serialized all stage traffic through one server; VERDICT r3 weak #4.)
"""
from __future__ import annotations

import os
import queue
import socket
import struct
import threading
from typing import Dict, Optional, Tuple

import numpy as np

_DTYPES = {
    0: np.float32, 1: np.float64, 2: np.float16, 3: np.int32,
    4: np.int64, 5: np.uint8, 6: np.bool_,
}
_DTYPE_IDS = {np.dtype(v): k for k, v in _DTYPES.items()}

_RECV_TIMEOUT_S = float(os.environ.get("PADDLE_PP_P2P_TIMEOUT", "300"))


def _pack(arr: np.ndarray) -> bytes:
    """SendRecvMeta analog: [dtype u8][ndim u8][dims i64...] + raw bytes."""
    arr = np.ascontiguousarray(arr)
    head = struct.pack("<BB", _DTYPE_IDS[arr.dtype], arr.ndim)
    head += struct.pack(f"<{arr.ndim}q", *arr.shape)
    return head + arr.tobytes()


def _unpack(buf: bytes) -> np.ndarray:
    dt_id, ndim = struct.unpack_from("<BB", buf, 0)
    shape = struct.unpack_from(f"<{ndim}q", buf, 2)
    off = 2 + 8 * ndim
    return np.frombuffer(buf, dtype=_DTYPES[dt_id],
                         offset=off).reshape(shape).copy()


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        c = sock.recv(min(n, 1 << 20))
        if not c:
            raise ConnectionError("pp p2p peer closed the connection")
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


def _local_host() -> str:
    """The address peers should dial: the interface that reaches the
    rendezvous master (multi-host), else loopback (single-host tests)."""
    master = os.environ.get("PADDLE_MASTER")
    if master:
        try:
            probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            probe.connect((master.split(":")[0],
                           int(master.split(":")[1])))
            host = probe.getsockname()[0]
            probe.close()
            if host and not host.startswith("0."):
                return host
        except OSError:
            pass
    return "127.0.0.1"


class P2PCommunicator:
    """Direct-socket p2p channels keyed (src_stage -> dst_stage, tag)."""

    def __init__(self, store, stage_id: int, prefix: str = "__pp_p2p__",
                 sub_rank: int = 0):
        """``sub_rank``: the TP (mp) rank within the stage when PP
        composes with TP — each mp-rank process publishes a DISTINCT
        listener (addr key ``{prefix}/addr/{stage}:{sub}``) and p2p is
        column-wise: sends dial the peer stage's communicator with the
        SAME sub_rank (Megatron's partial p2p pairing)."""
        self._store = store
        self.stage_id = stage_id
        self.sub_rank = sub_rank
        self._prefix = prefix
        self._send_socks: Dict[int, socket.socket] = {}
        self._send_locks: Dict[int, threading.Lock] = {}
        self._dial_mu = threading.Lock()
        self._queues: Dict[Tuple[int, str], "queue.Queue[bytes]"] = {}
        self._qlock = threading.Lock()
        self._bc_seq: Dict[str, int] = {}
        self._closed = False

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(64)
        port = self._listener.getsockname()[1]
        store.set(f"{prefix}/addr/{stage_id}:{sub_rank}",
                  f"{_local_host()}:{port}".encode())
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"pp-p2p-accept-{stage_id}")
        self._accept_thread.start()

    # -- receive side ------------------------------------------------------

    def _q(self, src: int, tag: str) -> "queue.Queue[bytes]":
        with self._qlock:
            return self._queues.setdefault((src, tag), queue.Queue())

    def _accept_loop(self):
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._reader_loop, args=(conn,),
                             daemon=True).start()

    def _reader_loop(self, conn: socket.socket):
        try:
            (src,) = struct.unpack("<i", _recv_exact(conn, 4))
            while True:
                head = _recv_exact(conn, 2)
                (tag_len,) = struct.unpack("<H", head)
                tag = _recv_exact(conn, tag_len).decode()
                (size,) = struct.unpack("<Q", _recv_exact(conn, 8))
                payload = _recv_exact(conn, size)
                self._q(src, tag).put(payload)
        except (ConnectionError, OSError):
            conn.close()  # peer done (normal teardown) or died

    # -- send side ---------------------------------------------------------

    def _resolve_addr(self, dst_stage: int) -> str:
        """Bounded address lookup: TCPStore.wait has no timeout, so it
        runs on a reaper thread — a peer that died before publishing its
        listener must produce a diagnostic, not a silent hang (the send
        side's analog of _RECV_TIMEOUT_S)."""
        res: "queue.Queue" = queue.Queue()
        key = f"{self._prefix}/addr/{dst_stage}:{self.sub_rank}"

        def _w():
            try:
                res.put(self._store.wait(key))
            except Exception as e:  # noqa: BLE001 — ferried to caller
                res.put(e)

        threading.Thread(target=_w, daemon=True).start()
        try:
            out = res.get(timeout=_RECV_TIMEOUT_S)
        except queue.Empty:
            raise TimeoutError(
                f"pp p2p dial(stage {dst_stage}) timed out after "
                f"{_RECV_TIMEOUT_S}s — peer never published its "
                "listener address (dead or not started)") from None
        if isinstance(out, Exception):
            raise out
        return out.decode()

    def _connect(self, addr: str) -> socket.socket:
        host, port = addr.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=60)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.sendall(struct.pack("<i", self.stage_id))
        return s

    def send(self, arr, dst_stage: int, tag: str = "act") -> None:
        if dst_stage not in self._send_socks:
            # resolve OUTSIDE the dial lock (a dead peer must not block
            # sends to other stages), then serialize the dial: two racing
            # first-sends must not create two connections —
            # per-connection TCP ordering is what makes the per-(src,
            # tag) streams FIFO
            addr = self._resolve_addr(dst_stage)
            with self._dial_mu:
                if dst_stage not in self._send_socks:
                    self._send_locks[dst_stage] = threading.Lock()
                    self._send_socks[dst_stage] = self._connect(addr)
        payload = _pack(np.asarray(arr))
        t = tag.encode()
        head = (struct.pack("<H", len(t)) + t
                + struct.pack("<Q", len(payload)))
        with self._send_locks[dst_stage]:
            sock = self._send_socks[dst_stage]
            # two sendalls: no header+payload concat — that would copy
            # every multi-MB activation a second time on the hot path
            sock.sendall(head)
            sock.sendall(payload)

    def recv(self, src_stage: int, tag: str = "act") -> np.ndarray:
        try:
            buf = self._q(src_stage, tag).get(timeout=_RECV_TIMEOUT_S)
        except queue.Empty:
            raise TimeoutError(
                f"pp p2p recv(stage {src_stage}, tag {tag!r}) timed out "
                f"after {_RECV_TIMEOUT_S}s — peer stage dead or schedule "
                "mismatch") from None
        return _unpack(buf)

    # -- partial p2p (the reference's partial_send/partial_recv) -----------
    # When PP composes with TP, each mp rank ships only ITS 1/mp slice of
    # the boundary tensor over its COLUMN's pipe wire
    # (p2p_communication.py:156-215 _partial_send): the wire carries 1/mp
    # of the bytes per rank. In the multi-process topology each mp-rank
    # pair runs its own communicator (``sub_rank``) and the mp group's
    # allgather reassembles (the reference's _partial_allgather is an mp
    # collective); ``recv_partial`` below is the single-receiver form
    # that pulls every slice over tags and reassembles in-process.

    def send_partial(self, arr, dst_stage: int, mp_degree: int,
                     mp_rank: int, tag: str = "act") -> None:
        a = np.ascontiguousarray(np.asarray(arr))
        flat = a.reshape(-1)
        if flat.size % mp_degree:
            raise ValueError(f"send_partial: {flat.size} elements not "
                             f"divisible by mp_degree {mp_degree}")
        step = flat.size // mp_degree
        self.send(flat[mp_rank * step:(mp_rank + 1) * step], dst_stage,
                  tag=f"{tag}/p{mp_rank}")

    def recv_partial(self, src_stage: int, mp_degree: int, shape,
                     tag: str = "act") -> np.ndarray:
        """Gather all mp slices of one boundary tensor and reassemble to
        ``shape`` (the receiving side's _partial_allgather)."""
        parts = [self.recv(src_stage, tag=f"{tag}/p{r}")
                 for r in range(mp_degree)]
        return np.concatenate([p.reshape(-1) for p in parts]).reshape(
            shape)

    # -- scalar broadcast (the _broadcast_final_loss analog) ---------------
    def bcast_scalar(self, value: Optional[float], src_stage: int,
                     tag: str = "loss") -> float:
        seq = self._bc_seq.get(tag, 0)
        self._bc_seq[tag] = seq + 1
        key = f"{self._prefix}/bcast/{src_stage}/{tag}/{seq}"
        if self.stage_id == src_stage:
            self._store.set(key, struct.pack("<d", float(value)))
            if seq >= 2:
                # self-cleaning window: every rank consumed seq-2 before
                # this rank could finish step seq-1 (the schedule joins
                # between steps), so the store never accumulates more
                # than 2 live keys per (src, tag)
                try:
                    self._store.delete_key(
                        f"{self._prefix}/bcast/{src_stage}/{tag}/{seq - 2}")
                except Exception:  # noqa: BLE001 — cleanup best-effort
                    pass
            return float(value)
        buf = self._store.wait(key)
        return struct.unpack("<d", buf)[0]

    def close(self):
        self._closed = True
        for s in self._send_socks.values():
            try:
                s.close()
            except OSError:
                pass
        try:
            self._listener.close()
        except OSError:
            pass

    def __del__(self):  # best-effort: daemon threads die with the process
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass
