"""Gradient-communication meta-optimizers.

~ fleet/meta_optimizers/ (gradient_merge_optimizer.py:20,
localsgd_optimizer.py:26, dgc_optimizer.py:21, fp16_allreduce_optimizer.py).
Eager wrappers around an inner optimizer; the compiled path gets the same
effects from GSPMD (grad psum) + microbatching, so these serve the
script-level strategy knobs.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...autograd import no_grad
from ...core.tensor import Tensor
from .. import collective as C


class GradientMergeOptimizer:
    """Accumulate grads for k steps, then apply (~ gradient_merge)."""

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self.inner = inner_optimizer
        self.k_steps = k_steps
        self.avg = avg
        self._acc = {}
        self._count = 0

    @no_grad()
    def step(self):
        self._count += 1
        for p in self.inner._parameters:
            if p._grad is not None:
                acc = self._acc.get(id(p))
                g = p._grad._value
                self._acc[id(p)] = g if acc is None else acc + g
                p._grad = None
        if self._count >= self.k_steps:
            for p in self.inner._parameters:
                acc = self._acc.get(id(p))
                if acc is not None:
                    if self.avg:
                        acc = acc / self._count
                    p._grad = Tensor(acc)
            self.inner.step()
            self.inner.clear_grad()
            self._acc = {}
            self._count = 0

    def clear_grad(self):
        for p in self.inner._parameters:
            p._grad = None

    clear_gradients = clear_grad

    def __getattr__(self, name):
        return getattr(self.inner, name)


class LocalSGDOptimizer:
    """Local updates with periodic parameter averaging (~ localsgd)."""

    def __init__(self, inner_optimizer, k_steps=1, group=None):
        self.inner = inner_optimizer
        self.k_steps = k_steps
        self.group = group
        self._count = 0

    @no_grad()
    def step(self):
        self.inner.step()
        self._count += 1
        if self._count % self.k_steps == 0:
            world = C.get_world_size(self.group)
            if world > 1:
                for p in self.inner._parameters:
                    C.all_reduce(p, group=self.group)
                    p._value = p._value / world

    def clear_grad(self):
        self.inner.clear_grad()

    clear_gradients = clear_grad

    def __getattr__(self, name):
        return getattr(self.inner, name)


class DGCMomentumOptimizer:
    """Deep gradient compression: top-k sparsified grad sync with local
    accumulation of the residual (~ dgc_optimizer + dgc_momentum_op).
    On TPU the compiled DP path makes this unnecessary (psum over ICI is
    cheap); kept for capability parity on slow-interconnect eager DP."""

    def __init__(self, inner_optimizer, rampup_begin_step=0, sparsity=0.999,
                 group=None):
        self.inner = inner_optimizer
        self.sparsity = sparsity
        self.group = group
        self._residual = {}

    @no_grad()
    def step(self):
        world = C.get_world_size(self.group)
        for p in self.inner._parameters:
            if p._grad is None:
                continue
            g = p._grad._value + self._residual.get(id(p), 0.0)
            k = max(1, int(round(g.size * (1 - self.sparsity))))
            flat = jnp.abs(g.reshape(-1))
            if k < flat.shape[0]:
                thresh = jnp.sort(flat)[-k]
                mask = (jnp.abs(g) >= thresh)
            else:
                mask = jnp.ones_like(g, bool)
            sparse_g = jnp.where(mask, g, 0.0)
            self._residual[id(p)] = g - sparse_g
            p._grad = Tensor(sparse_g)
            if world > 1:
                C.all_reduce(p._grad, group=self.group)
                p._grad._value = p._grad._value / world
        self.inner.step()

    def clear_grad(self):
        self.inner.clear_grad()

    clear_gradients = clear_grad

    def __getattr__(self, name):
        return getattr(self.inner, name)
