"""Parallelization planner: search (dp, sep, mp, pp) over the cost model.

~ python/paddle/distributed/auto_parallel/planner.py:826 (PlanSpace
enumerating dist-attr combinations + MCMC search) and tuner/ — here the
search space is the 4-axis factorization lattice of the device count,
ranked by the analytic CostModel; infeasible plans (OOM) are filtered
first, mirroring the reference planner's constraint pass. The 'sep'
(sequence/context-parallel) axis exceeds the reference (SURVEY §5):
ring-attention KV rotation is costed so long-sequence models can trade
a sep slice against dp/mp.
"""
from __future__ import annotations

from typing import List, Optional

from .cost_model import Cluster, CostModel, ModelSpec


def _factorizations(n: int) -> List[tuple]:
    """All (dp, sep, mp, pp) with dp*sep*mp*pp == n."""
    out = []
    for dp in range(1, n + 1):
        if n % dp:
            continue
        rem1 = n // dp
        for sep in range(1, rem1 + 1):
            if rem1 % sep:
                continue
            rem2 = rem1 // sep
            for mp in range(1, rem2 + 1):
                if rem2 % mp:
                    continue
                out.append((dp, sep, mp, rem2 // mp))
    return out


class Plan:
    def __init__(self, dp, mp, pp, cost, sep=1):
        self.dp, self.mp, self.pp, self.sep = dp, mp, pp, sep
        self.cost = cost

    @property
    def mesh_shape(self):
        return {"data": self.dp, "sep": self.sep, "model": self.mp,
                "pipe": self.pp}

    def __repr__(self):
        return (f"Plan(dp={self.dp}, sep={self.sep}, mp={self.mp}, "
                f"pp={self.pp}, "
                f"step={self.cost['total'] * 1e3:.1f}ms, "
                f"mem={self.cost['memory_bytes'] / 1e9:.1f}GB)")


class Planner:
    """~ planner.py Planner: enumerate, filter by memory, rank by time."""

    def __init__(self, cluster: Optional[Cluster] = None,
                 model: Optional[ModelSpec] = None,
                 max_mp: Optional[int] = None,
                 max_pp: Optional[int] = None,
                 max_sep: Optional[int] = None,
                 eff: Optional[float] = None):
        self.cluster = cluster or Cluster()
        self.model = model or ModelSpec()
        self.max_mp = max_mp
        self.max_pp = max_pp
        self.max_sep = max_sep
        self.eff = eff

    def plans(self, include_oom: bool = False) -> List[Plan]:
        cm = CostModel(self.cluster, self.model, eff=self.eff)
        out = []
        for dp, sep, mp, pp in _factorizations(self.cluster.n_devices):
            if self.max_mp and mp > self.max_mp:
                continue
            if self.max_pp and pp > self.max_pp:
                continue
            if self.max_sep and sep > self.max_sep:
                continue
            if pp > 1 and self.model.n_layers % pp:
                continue
            if self.model.global_batch % dp:
                continue
            if self.model.seq % sep:
                continue
            # a sep chunk must hold at least one flash block (512) for
            # the ring kernels to run at their tuned tile sizes
            if sep > 1 and self.model.seq // sep < 512:
                continue
            cost = cm.estimate(dp, mp, pp, sep=sep)
            if cost["fits"] or include_oom:
                out.append(Plan(dp, mp, pp, cost, sep=sep))
        out.sort(key=lambda p: (not p.cost["fits"], p.cost["total"]))
        return out

    def best(self) -> Plan:
        plans = self.plans(include_oom=True)
        if not plans:
            raise RuntimeError("no feasible plan found")
        return plans[0]

    def to_mesh(self, plan: Plan):
        """Materialize the chosen plan as a jax Mesh (axes
        data/sep/model/pipe, singleton axes dropped)."""
        import jax
        import numpy as np
        from jax.sharding import Mesh
        shape = [(k, v) for k, v in plan.mesh_shape.items() if v > 1]
        if not shape:
            shape = [("data", 1)]
        devs = np.asarray(jax.devices()[:self.cluster.n_devices])
        return Mesh(devs.reshape(tuple(v for _, v in shape)),
                    tuple(k for k, _ in shape))
