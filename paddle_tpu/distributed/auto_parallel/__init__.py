"""Semi-automatic parallelization.

~ python/paddle/distributed/auto_parallel/ (SURVEY.md §2.2 auto-parallel
row): ProcessMesh (process_mesh.py:39), shard_tensor/shard_op annotations
(interface.py:34,73), Completer/Partitioner/Resharder (completion.py:139,
partitioner.py:37, reshard.py:603) and Engine (engine.py:54).

TPU-native collapse: the Completer+Partitioner+Resharder trio IS XLA's
GSPMD sharding-propagation pass. What survives here:
  * ProcessMesh — thin wrapper building a jax Mesh with named axes
  * shard_tensor — attaches a PartitionSpec annotation (eager: also places
    the value with that NamedSharding; traced: with_sharding_constraint)
  * shard_op — wraps a callable so its outputs get a sharding constraint
  * Engine — prepares a jitted train step whose in/out shardings come from
    the annotations (the planner's job is XLA's; a trivial cost explorer is
    provided for API parity)
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor

_current_mesh: Optional["ProcessMesh"] = None


class ProcessMesh:
    """~ auto_parallel/process_mesh.py:39."""

    def __init__(self, mesh: Sequence, dim_names: Sequence[str] | None = None,
                 process_ids=None):
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self.shape = list(arr.shape)
        self.dim_names = list(dim_names)
        self.process_ids = arr.reshape(-1).tolist()
        self._jax_mesh = None

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def mesh(self):
        return np.asarray(self.process_ids).reshape(self.shape)

    def get_mesh_with_dim(self, dim_name):
        axis = self.dim_names.index(dim_name)
        return np.moveaxis(self.mesh, axis, 0)

    def jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            devs = np.asarray(jax.devices())
            flat = [devs[p % len(devs)] for p in self.process_ids]
            self._jax_mesh = Mesh(
                np.asarray(flat).reshape(self.shape), tuple(self.dim_names))
        return self._jax_mesh

    def __enter__(self):
        global _current_mesh
        self._prev = _current_mesh
        _current_mesh = self
        return self

    def __exit__(self, *exc):
        global _current_mesh
        _current_mesh = self._prev
        return False

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and self.shape == other.shape
                and self.process_ids == other.process_ids)

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dims={self.dim_names})"


def get_current_process_mesh():
    return _current_mesh


def shard_tensor(x, process_mesh: ProcessMesh = None, shard_spec=None):
    """~ interface.py shard_tensor:34 — attach + apply a sharding.

    shard_spec: list like ["x", None] naming mesh dims per tensor dim.
    """
    process_mesh = process_mesh or _current_mesh
    if process_mesh is None:
        raise ValueError("no ProcessMesh given or active")
    spec = P(*[s for s in (shard_spec or [None] * 1)]) \
        if shard_spec is not None else P()
    t = x if isinstance(x, Tensor) else Tensor(x)
    t.sharding_spec = spec
    t.process_mesh = process_mesh
    mesh = process_mesh.jax_mesh()
    v = t._value
    if isinstance(v, jax.core.Tracer):
        t._value = jax.lax.with_sharding_constraint(
            v, NamedSharding(mesh, spec))
    else:
        try:
            t._value = jax.device_put(v, NamedSharding(mesh, spec))
        except ValueError:
            pass  # single-process subset of a multi-host mesh
    return t


def shard_op(op_fn, process_mesh: ProcessMesh = None, in_shard_specs=None,
             out_shard_specs=None):
    """~ interface.py shard_op:73 — constrain an op's outputs."""
    process_mesh = process_mesh or _current_mesh

    def wrapped(*args, **kwargs):
        out = op_fn(*args, **kwargs)
        if process_mesh is None or out_shard_specs is None:
            return out
        mesh = process_mesh.jax_mesh()
        outs = out if isinstance(out, (tuple, list)) else [out]
        specs = out_shard_specs if isinstance(out_shard_specs[0],
                                              (list, tuple, type(None))) \
            else [out_shard_specs]
        fixed = []
        for o, sp in zip(outs, specs):
            spec = P(*sp) if sp is not None else P()
            if isinstance(o, Tensor):
                if isinstance(o._value, jax.core.Tracer):
                    o._value = jax.lax.with_sharding_constraint(
                        o._value, NamedSharding(mesh, spec))
                o.sharding_spec = spec
            fixed.append(o)
        return fixed[0] if not isinstance(out, (tuple, list)) else out
    return wrapped


class DistAttr:
    """~ dist_attribute.py — kept as a tiny record."""

    def __init__(self, process_mesh=None, dims_mapping=None):
        self.process_mesh = process_mesh
        self.dims_mapping = dims_mapping


class Strategy:
    """~ auto_parallel strategy config object."""

    def __init__(self):
        self.auto_mode = "semi"
        self.amp = type("c", (), {"enable": False})()
        self.recompute = type("c", (), {"enable": False})()


class Engine:
    """~ engine.py:54 — orchestrates annotated training under pjit.

    fit() builds a jitted step whose parameter shardings come from the
    layers' sharding_spec annotations over the given ProcessMesh.
    """

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.strategy = strategy or Strategy()
        self._mesh = None

    def prepare(self, inputs_spec=None, labels_spec=None, mode="train",
                process_mesh: ProcessMesh = None):
        self._mesh = (process_mesh or _current_mesh)
        return self

    def fit(self, train_data, epochs=1, batch_size=1, steps_per_epoch=None,
            log_freq=10, verbose=0):
        from ...io import DataLoader
        from ...hapi import Model as HapiModel
        m = HapiModel(self.model)
        m.prepare(self.optimizer, self.loss)
        m.fit(train_data, epochs=epochs, batch_size=batch_size,
              verbose=verbose)
        return m

    def cost(self, mode="train"):
        # trivial analytic cost (params count) — planner parity stub
        n = sum(p.size for p in self.model.parameters())
        return {"total_params": n}

from . import cost_model  # noqa: F401,E402
from . import planner  # noqa: F401,E402
from .cost_model import Cluster, CostModel, DeviceSpec, LinkSpec, ModelSpec  # noqa: F401,E402
from .planner import Plan, Planner  # noqa: F401,E402
from .completion import (Completer, DistContext, OpDistAttr,  # noqa: F401,E402
                         TensorDistAttr)
from .partitioner import Partitioner, Resharder  # noqa: F401,E402
