"""Dist-attribute completion over captured static Programs.

~ reference auto_parallel/completion.py:139 (Completer.complete_forward_
annotation: propagate ProcessMesh + dims_mapping through every op from the
user's shard_tensor annotations, :726 update loop). Same contract, over the
TPU build's functional OpNode DAG (static/graph.py) instead of ProgramDesc:
dims_mapping is a per-tensor-dim list of mesh-axis indices (-1 =
replicated), exactly the reference's dist_attribute.py convention.

The completed DistContext feeds the Partitioner (per-rank local programs)
and the Resharder (communication insertion) — golden-testable program text,
the reference's auto_parallel test style (SURVEY.md §4).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


def _var_name(v) -> str:
    return getattr(v, "name", None) or f"lit_{id(v)}"


def _ndim(v) -> int:
    shp = getattr(v, "shape", None)
    if shp is None:
        return 0
    return len(shp)


class TensorDistAttr:
    """~ dist_attribute.py TensorDistributedAttribute."""

    def __init__(self, dims_mapping: List[int], is_partial_on: frozenset =
                 frozenset()):
        self.dims_mapping = list(dims_mapping)
        # mesh axes over which the value is a partial sum (pending psum)
        self.is_partial_on = frozenset(is_partial_on)

    def __repr__(self):
        p = f" partial{sorted(self.is_partial_on)}" if self.is_partial_on \
            else ""
        return f"{self.dims_mapping}{p}"


class OpDistAttr:
    """~ dist_attribute.py OperatorDistributedAttribute."""

    def __init__(self, op_name: str, inputs: List[str], outputs: List[str],
                 in_attrs: List[TensorDistAttr],
                 out_attrs: List[TensorDistAttr]):
        self.op_name = op_name
        self.inputs = inputs
        self.outputs = outputs
        self.in_attrs = in_attrs
        self.out_attrs = out_attrs


class DistContext:
    """~ dist_context.py DistributedContext: mesh + all completed attrs."""

    def __init__(self, process_mesh):
        self.process_mesh = process_mesh
        self.var_attrs: Dict[str, TensorDistAttr] = {}
        self.var_shapes: Dict[str, List[int]] = {}
        self.ops: List[OpDistAttr] = []
        self.outputs: List[str] = []  # fetch vars: partials resolve here

    def set_var(self, name, attr, shape=None):
        self.var_attrs[name] = attr
        if shape is not None:
            self.var_shapes[name] = list(shape)

    def get_var(self, name) -> Optional[TensorDistAttr]:
        return self.var_attrs.get(name)


def _rep(nd):
    return TensorDistAttr([-1] * nd)


class Completer:
    """~ completion.py:139 — forward dist-attr propagation."""

    def __init__(self, process_mesh,
                 annotations: Dict[str, Sequence[Optional[str]]]):
        """annotations: var name -> shard_spec (mesh dim NAMES per tensor
        dim, None = replicated), the shard_tensor surface."""
        self.mesh = process_mesh
        self.annotations = {}
        for name, spec in annotations.items():
            self.annotations[name] = [
                -1 if s is None else process_mesh.dim_names.index(s)
                for s in spec]

    # -- per-op propagation rules ------------------------------------------
    def _prop(self, op_name, in_attrs: List[TensorDistAttr],
              in_vars) -> (List[TensorDistAttr], TensorDistAttr):
        """Returns (REQUIRED input attrs, output attr). A required attr that
        differs from the producer's is a reshard edge."""
        ew = {"relu", "tanh", "sigmoid", "gelu", "silu", "add", "subtract",
              "multiply", "divide", "scale", "softmax", "exp", "dropout"}
        if op_name in ("linear", "matmul"):
            x, w = in_attrs[0], in_attrs[1]
            xm = list(x.dims_mapping)
            wm = list(w.dims_mapping)
            k_x, k_w = xm[-1], wm[0] if wm else -1
            out = xm[:-1] + [wm[-1] if len(wm) > 1 else -1]
            partial = frozenset()
            req_x, req_w = list(xm), list(wm)
            if k_x != k_w:
                # contracted dim must agree: gather the sharded side
                req_x[-1] = -1
                if wm:
                    req_w[0] = -1
            elif k_x != -1:
                # both sharded the contraction dim -> partial sum (the
                # reference inserts c_allreduce_sum here, reshard.py:603)
                partial = frozenset({k_x})
            req = [TensorDistAttr(req_x), TensorDistAttr(req_w)]
            if len(in_attrs) > 2:  # bias: follow output's last dim
                req.append(TensorDistAttr([out[-1]]))
            return req, TensorDistAttr(out, partial)
        if op_name in ("mean", "sum", "reduce_mean", "reduce_sum"):
            x = in_attrs[0]
            partial = frozenset(m for m in x.dims_mapping if m != -1) \
                | x.is_partial_on
            return [x], TensorDistAttr([], partial)
        if op_name in ew:
            base = next((a for a in in_attrs if a.dims_mapping), None)
            out = list(base.dims_mapping) if base else []
            req = []
            for a, v in zip(in_attrs, in_vars):
                nd = _ndim(v)
                req.append(TensorDistAttr(out[-nd:] if nd else []))
            partial = frozenset().union(*[a.is_partial_on
                                          for a in in_attrs]) \
                if in_attrs else frozenset()
            return req, TensorDistAttr(out, partial)
        if op_name in ("transpose", "t"):
            x = in_attrs[0]
            return [x], TensorDistAttr(list(reversed(x.dims_mapping)),
                                       x.is_partial_on)
        # unknown op: demand fully replicated inputs, replicated out
        req = [_rep(len(a.dims_mapping)) for a in in_attrs]
        nd_out = len(in_attrs[0].dims_mapping) if in_attrs else 0
        return req, _rep(nd_out)

    # -- the walk -----------------------------------------------------------
    def complete_forward_annotation(self, outputs) -> DistContext:
        """outputs: fetch StaticVars; walks producers topologically."""
        ctx = DistContext(self.mesh)
        if not isinstance(outputs, (list, tuple)):
            outputs = [outputs]
        ctx.outputs = [_var_name(o) for o in outputs]

        # topo order of OpNodes (post-order from outputs)
        order, seen = [], set()

        def visit(v):
            node = getattr(v, "_node", None)
            if node is None or id(node) in seen:
                return
            seen.add(id(node))
            for a in node.args:
                if hasattr(a, "_node") or hasattr(a, "shape"):
                    visit(a)
            order.append(node)

        for o in outputs:
            visit(o)

        def attr_for(v) -> TensorDistAttr:
            name = _var_name(v)
            if name in ctx.var_attrs:
                return ctx.var_attrs[name]
            nd = _ndim(v)
            if name in self.annotations:
                m = self.annotations[name]
                a = TensorDistAttr(m + [-1] * (nd - len(m)))
            else:
                a = _rep(nd)
            ctx.set_var(name, a, getattr(v, "shape", None))
            return a

        for node in order:
            tens_in = [a for a in node.args
                       if hasattr(a, "shape") and _ndim(a) >= 0
                       and hasattr(a, "dtype")]
            in_attrs = [attr_for(a) for a in tens_in]
            req, out_attr = self._prop(node.name, in_attrs, tens_in)
            for ov in node.out_vars:
                ctx.set_var(_var_name(ov), out_attr,
                            getattr(ov, "shape", None))
            ctx.ops.append(OpDistAttr(
                node.name,
                [_var_name(a) for a in tens_in],
                [_var_name(ov) for ov in node.out_vars],
                req, [out_attr]))
        return ctx
