"""Per-rank program partitioning + reshard (comm insertion).

~ reference auto_parallel/partitioner.py:37 (Partitioner.partition: split
the serial program into one program per rank with LOCAL shapes, :67) and
reshard.py:603 (Resharder: insert communication where producer/consumer
dist attrs disagree — allgather for shard→replicate, slice for
replicate→shard, c_allreduce_sum to resolve partial sums).

Output is deterministic program TEXT per rank — the reference's
auto_parallel tests assert on generated program ops/attrs the same way
(compiler-style golden testing, SURVEY.md §4).
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from .completion import DistContext, OpDistAttr, TensorDistAttr


def _local_shape(shape, attr: TensorDistAttr, mesh) -> List[int]:
    out = []
    for d, (sz, m) in enumerate(zip(shape, attr.dims_mapping)):
        if m == -1 or sz in (-1, None):
            out.append(sz)
        else:
            out.append(sz // mesh.shape[m])
    return out


class Resharder:
    """~ reshard.py:603 — computes the comm ops an edge needs."""

    def __init__(self, ctx: DistContext):
        self.ctx = ctx

    def edge_ops(self, var: str, have: TensorDistAttr,
                 want: TensorDistAttr) -> List[str]:
        mesh = self.ctx.process_mesh
        ops = []
        # resolve pending partial sums first
        for ax in sorted(have.is_partial_on - want.is_partial_on):
            ops.append(f"c_allreduce_sum({var}, mesh_dim={ax}"
                       f"['{mesh.dim_names[ax]}'])")
        for d, (h, w) in enumerate(zip(have.dims_mapping,
                                       want.dims_mapping)):
            if h == w:
                continue
            if h != -1 and w == -1:
                ops.append(f"c_allgather({var}, dim={d}, mesh_dim={h}"
                           f"['{mesh.dim_names[h]}'])")
            elif h == -1 and w != -1:
                ops.append(f"slice({var}, dim={d}, mesh_dim={w}"
                           f"['{mesh.dim_names[w]}'])")
            else:
                ops.append(f"all_to_all({var}, dim={d}, {h}->{w})")
        return ops


class Partitioner:
    """~ partitioner.py:37 — emit one local program per rank."""

    def __init__(self, ctx: DistContext):
        self.ctx = ctx
        self.resharder = Resharder(ctx)

    def partition(self, rank: int) -> str:
        mesh = self.ctx.process_mesh
        coords = {}
        flat = list(mesh.process_ids)
        if rank in flat:
            import numpy as np
            idx = np.unravel_index(flat.index(rank), mesh.shape)
            coords = {mesh.dim_names[i]: int(idx[i])
                      for i in range(len(mesh.shape))}
        lines = [f"rank {rank} coords {coords} on mesh"
                 f"{list(mesh.shape)} axes {mesh.dim_names}:"]
        produced: Dict[str, TensorDistAttr] = {}

        def fmt_var(name, attr):
            shp = self.ctx.var_shapes.get(name)
            if shp is None:
                return name
            local = _local_shape(shp, attr, mesh)
            return f"{name}{local}"

        for op in self.ctx.ops:
            # reshard edges: producer attr vs this op's required attr
            for vname, want in zip(op.inputs, op.in_attrs):
                have = produced.get(vname, self.ctx.get_var(vname))
                if have is None:
                    continue
                for c in self.resharder.edge_ops(vname, have, want):
                    lines.append(f"  {c}")
                produced[vname] = want
            ins = ", ".join(fmt_var(v, a)
                            for v, a in zip(op.inputs, op.in_attrs))
            outs = ", ".join(fmt_var(v, a)
                             for v, a in zip(op.outputs, op.out_attrs))
            attr_s = " in=" + str([a.dims_mapping for a in op.in_attrs]) \
                + " out=" + str(op.out_attrs[0]) if op.out_attrs else ""
            lines.append(f"  {op.op_name}({ins}) -> {outs} {attr_s}")
            for vname, a in zip(op.outputs, op.out_attrs):
                produced[vname] = a
        # fetch boundary: pending partial sums must be resolved before the
        # value leaves the program (~ reshard.py resolving partial at use)
        for vname in self.ctx.outputs:
            have = produced.get(vname, self.ctx.get_var(vname))
            if have is None or not have.is_partial_on:
                continue
            want = TensorDistAttr(have.dims_mapping)
            for c in self.resharder.edge_ops(vname, have, want):
                lines.append(f"  {c}")
            produced[vname] = want
        return "\n".join(lines)

    def partition_all(self) -> Dict[int, str]:
        return {r: self.partition(r)
                for r in self.ctx.process_mesh.process_ids}
