"""Cost model for parallelization planning.

~ python/paddle/distributed/auto_parallel/cost_model.py:185 (+ cost/ op-cost
classes, cluster.py:395 device/link modeling): analytic estimates of
compute time (FLOPs / peak), collective time (ring allreduce / all-gather /
all-to-all over link bandwidth) and pipeline bubble, used by the Planner to
rank (dp, mp, pp) factorizations.

TPU numbers default to a v5p-ish chip (bf16 peak, ICI bandwidth per
direction); override via ``Cluster``.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class DeviceSpec:
    """~ cluster.py Device: one accelerator."""
    peak_flops: float = 459e12        # bf16 FLOP/s (v5p)
    mem_bytes: float = 95e9           # HBM per chip
    mem_bw: float = 2.76e12           # HBM bytes/s


@dataclass
class LinkSpec:
    """~ cluster.py Link: ICI (intra-slice) or DCN (cross-slice)."""
    bandwidth: float = 9e10           # bytes/s per direction per link (ICI)
    latency: float = 1e-6


@dataclass
class Cluster:
    """~ cluster.py Cluster — homogeneous mesh of devices."""
    n_devices: int = 8
    device: DeviceSpec = field(default_factory=DeviceSpec)
    ici: LinkSpec = field(default_factory=LinkSpec)
    dcn: LinkSpec = field(default_factory=LinkSpec(
        bandwidth=2.5e10, latency=1e-4).__class__)

    def __post_init__(self):
        if isinstance(self.dcn, type):
            self.dcn = LinkSpec(bandwidth=2.5e10, latency=1e-4)


class CommCost:
    """Collective time estimates on a ring of ``n`` devices."""

    def __init__(self, link: LinkSpec, n: int):
        self.link = link
        self.n = max(1, n)

    def all_reduce(self, nbytes: float) -> float:
        if self.n <= 1:
            return 0.0
        return (2 * (self.n - 1) / self.n) * nbytes / self.link.bandwidth \
            + 2 * (self.n - 1) * self.link.latency

    def all_gather(self, nbytes_per_shard: float) -> float:
        if self.n <= 1:
            return 0.0
        return (self.n - 1) * nbytes_per_shard / self.link.bandwidth \
            + (self.n - 1) * self.link.latency

    reduce_scatter = all_gather

    def all_to_all(self, nbytes_total: float) -> float:
        if self.n <= 1:
            return 0.0
        return (self.n - 1) / self.n * nbytes_total / self.link.bandwidth \
            + (self.n - 1) * self.link.latency

    def p2p(self, nbytes: float) -> float:
        return nbytes / self.link.bandwidth + self.link.latency


@dataclass
class ModelSpec:
    """Transformer-LM shape for planning (the role of the serial program +
    dist attrs in the reference's cost model). ``global_batch`` is fixed
    across candidate plans — dp divides it."""
    n_layers: int = 32
    hidden: int = 4096
    intermediate: int = 11008
    vocab: int = 32000
    seq: int = 2048
    global_batch: int = 64
    bytes_per_param: int = 2          # bf16

    @property
    def n_params(self) -> int:
        per_layer = (4 * self.hidden * self.hidden
                     + 3 * self.hidden * self.intermediate)
        return self.n_layers * per_layer + 2 * self.vocab * self.hidden

    def step_flops(self) -> float:
        """Total training FLOPs of one global step (all replicas)."""
        tokens = self.global_batch * self.seq
        attn = 12 * self.n_layers * self.hidden * self.seq * tokens
        return 6 * self.n_params * tokens + attn


class CostModel:
    """Per-step time estimate for a (dp, mp, pp) plan
    (~ cost_model.py CostModel.get_runtime)."""

    def __init__(self, cluster: Cluster, model: ModelSpec):
        self.cluster = cluster
        self.model = model

    def estimate(self, dp: int, mp: int, pp: int,
                 n_microbatches: Optional[int] = None) -> Dict[str, float]:
        c = self.cluster
        m = self.model
        if dp * mp * pp != c.n_devices:
            raise ValueError(f"dp*mp*pp = {dp * mp * pp} != "
                             f"{c.n_devices} devices")
        if m.global_batch % dp:
            raise ValueError(f"global_batch {m.global_batch} not divisible "
                             f"by dp {dp}")
        batch_per_replica = m.global_batch // dp
        M = n_microbatches or max(1, 4 * pp)
        # compute: the global step's FLOPs spread over every device (dp
        # splits batch, mp splits matmuls, pp splits layers)
        eff = 0.55  # achievable fraction of peak for dense transformer steps
        compute = m.step_flops() / (dp * mp * pp) / (c.device.peak_flops * eff)

        comm_mp = CommCost(c.ici, mp)
        comm_dp = CommCost(c.ici, dp)
        comm_pp = CommCost(c.ici, pp)

        # tensor-parallel: 4 allreduces of (b, s, h) activations per layer
        # (2 fwd + 2 bwd), layers split over pp
        act_bytes = batch_per_replica * m.seq * m.hidden \
            * m.bytes_per_param / M
        tp_time = (m.n_layers / pp) * 4 * M * comm_mp.all_reduce(act_bytes) \
            if mp > 1 else 0.0

        # data-parallel gradient allreduce of this rank's param shard
        grad_bytes = m.n_params / (mp * pp) * 4  # f32 grads
        dp_time = comm_dp.all_reduce(grad_bytes) if dp > 1 else 0.0

        # pipeline: bubble fraction + p2p per microbatch boundary
        bubble = (pp - 1) / (M + pp - 1) if pp > 1 else 0.0
        p2p_time = 2 * M * (pp - 1) * comm_pp.p2p(act_bytes) / max(1, pp) \
            if pp > 1 else 0.0

        total = (compute + tp_time) / (1 - bubble) + dp_time + p2p_time

        # memory per device: params + grads + adam moments + activations
        param_b = m.n_params / (mp * pp) * m.bytes_per_param
        opt_b = m.n_params / (mp * pp) * 8  # two f32 moments
        grad_b = m.n_params / (mp * pp) * 4
        act_b = (m.n_layers / pp) * batch_per_replica * m.seq * m.hidden \
            * m.bytes_per_param * 4 / M  # remat'd working set
        mem = param_b + opt_b + grad_b + act_b
        return {"total": total, "compute": compute, "tp_comm": tp_time,
                "dp_comm": dp_time, "pp_p2p": p2p_time, "bubble": bubble,
                "memory_bytes": mem, "fits": mem < c.device.mem_bytes}
