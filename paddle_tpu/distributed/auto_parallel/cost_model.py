"""Cost model for parallelization planning.

~ python/paddle/distributed/auto_parallel/cost_model.py:185 (+ cost/ op-cost
classes, cluster.py:395 device/link modeling): analytic estimates of
compute time (FLOPs / peak), collective time (ring allreduce / all-gather /
all-to-all over link bandwidth) and pipeline bubble, used by the Planner to
rank (dp, mp, pp) factorizations.

TPU numbers default to a v5p-ish chip (bf16 peak, ICI bandwidth per
direction); override via ``Cluster``.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class DeviceSpec:
    """~ cluster.py Device: one accelerator."""
    peak_flops: float = 459e12        # bf16 FLOP/s (v5p)
    mem_bytes: float = 95e9           # HBM per chip
    mem_bw: float = 2.76e12           # HBM bytes/s


@dataclass
class LinkSpec:
    """~ cluster.py Link: ICI (intra-slice) or DCN (cross-slice)."""
    bandwidth: float = 9e10           # bytes/s per direction per link (ICI)
    latency: float = 1e-6


@dataclass
class Cluster:
    """~ cluster.py Cluster — homogeneous mesh of devices."""
    n_devices: int = 8
    device: DeviceSpec = field(default_factory=DeviceSpec)
    ici: LinkSpec = field(default_factory=LinkSpec)
    dcn: LinkSpec = field(default_factory=LinkSpec(
        bandwidth=2.5e10, latency=1e-4).__class__)

    def __post_init__(self):
        if isinstance(self.dcn, type):
            self.dcn = LinkSpec(bandwidth=2.5e10, latency=1e-4)


class CommCost:
    """Collective time estimates on a ring of ``n`` devices."""

    def __init__(self, link: LinkSpec, n: int):
        self.link = link
        self.n = max(1, n)

    def all_reduce(self, nbytes: float) -> float:
        if self.n <= 1:
            return 0.0
        return (2 * (self.n - 1) / self.n) * nbytes / self.link.bandwidth \
            + 2 * (self.n - 1) * self.link.latency

    def all_gather(self, nbytes_per_shard: float) -> float:
        if self.n <= 1:
            return 0.0
        return (self.n - 1) * nbytes_per_shard / self.link.bandwidth \
            + (self.n - 1) * self.link.latency

    reduce_scatter = all_gather

    def all_to_all(self, nbytes_total: float) -> float:
        if self.n <= 1:
            return 0.0
        return (self.n - 1) / self.n * nbytes_total / self.link.bandwidth \
            + (self.n - 1) * self.link.latency

    def p2p(self, nbytes: float) -> float:
        return nbytes / self.link.bandwidth + self.link.latency


@dataclass
class ModelSpec:
    """Transformer-LM shape for planning (the role of the serial program +
    dist attrs in the reference's cost model). ``global_batch`` is fixed
    across candidate plans — dp divides it."""
    n_layers: int = 32
    hidden: int = 4096
    intermediate: int = 11008
    vocab: int = 32000
    seq: int = 2048
    global_batch: int = 64
    bytes_per_param: int = 2          # bf16
    # head geometry (GQA-aware params + attention FLOPs + ring-KV bytes;
    # chip validation showed the MHA-only form misstates GQA rows by
    # ~10% params and the TP-shard row by 3x flops). Defaults keep the
    # classic MHA identity n_heads * head_dim == hidden.
    n_heads: Optional[int] = None
    kv_heads: Optional[int] = None
    head_dim: Optional[int] = None
    # K+V width per token for the ring-attention rotation; None derives
    # 2 * kv_heads * head_dim * bytes from the head geometry
    kv_bytes_per_token: Optional[int] = None

    @property
    def q_width(self) -> int:
        if self.n_heads and self.head_dim:
            return self.n_heads * self.head_dim
        return self.hidden

    @property
    def kv_width(self) -> int:
        if self.kv_heads and self.head_dim:
            return self.kv_heads * self.head_dim
        return self.q_width

    @property
    def n_params(self) -> int:
        attn = (2 * self.hidden * self.q_width      # q + o projections
                + 2 * self.hidden * self.kv_width)  # k + v projections
        per_layer = attn + 3 * self.hidden * self.intermediate
        return self.n_layers * per_layer + 2 * self.vocab * self.hidden

    def step_flops(self) -> float:
        """Total training FLOPs of one global step (all replicas)."""
        tokens = self.global_batch * self.seq
        attn = 12 * self.n_layers * self.q_width * self.seq * tokens
        return 6 * self.n_params * tokens + attn


class CostModel:
    """Per-step time estimate for a (dp, sep, mp, pp) plan
    (~ cost_model.py CostModel.get_runtime)."""

    # Achievable fraction of peak for dense bf16 transformer steps.
    # Chip-calibrated round 5 (tools/cost_validate.py publishes the
    # predicted-vs-measured table): single-chip measurements on v5e span
    # 0.59 (8B TP=8 shard shapes + zero-sliced adamw) to 0.82 (GQA best
    # config); 0.60 is the sharded-shape value — the regime pod plans
    # actually run in — and is conservative for fat single-chip configs.
    DEFAULT_EFF = 0.60

    def __init__(self, cluster: Cluster, model: ModelSpec,
                 eff: Optional[float] = None):
        self.cluster = cluster
        self.model = model
        # `eff or DEFAULT_EFF` silently swallowed an explicit eff=0.0
        # (round-5 advice #5): only None means "use the default", and a
        # non-physical efficiency is a caller bug, not a fallback
        if eff is not None and not 0.0 < eff <= 1.0:
            raise ValueError(f"eff {eff!r} must be in (0, 1]")
        self.eff = self.DEFAULT_EFF if eff is None else eff

    def estimate(self, dp: int, mp: int, pp: int,
                 n_microbatches: Optional[int] = None,
                 sep: int = 1) -> Dict[str, float]:
        c = self.cluster
        m = self.model
        if dp * mp * pp * sep != c.n_devices:
            raise ValueError(f"dp*mp*pp*sep = {dp * mp * pp * sep} != "
                             f"{c.n_devices} devices")
        if m.global_batch % dp:
            raise ValueError(f"global_batch {m.global_batch} not divisible "
                             f"by dp {dp}")
        if m.seq % sep:
            raise ValueError(f"seq {m.seq} not divisible by sep {sep}")
        batch_per_replica = m.global_batch // dp
        M = n_microbatches or max(1, 4 * pp)
        # compute: the global step's FLOPs spread over every device (dp
        # splits batch, mp splits matmuls, pp splits layers, sep splits
        # the sequence)
        compute = m.step_flops() / (dp * mp * pp * sep) \
            / (c.device.peak_flops * self.eff)

        comm_mp = CommCost(c.ici, mp)
        comm_pp = CommCost(c.ici, pp)
        comm_sep = CommCost(c.ici, sep)

        # tensor-parallel: 4 allreduces of (b, s_local, h) activations
        # per layer (2 fwd + 2 bwd), layers split over pp, seq over sep
        act_bytes = batch_per_replica * (m.seq // sep) * m.hidden \
            * m.bytes_per_param / M
        tp_time = (m.n_layers / pp) * 4 * M * comm_mp.all_reduce(act_bytes) \
            if mp > 1 else 0.0

        # sequence/context parallel: ring attention rotates the local
        # K+V chunk (sep-1) times per layer, fwd + bwd (the bwd ring also
        # rotates dK/dV accumulators — x2 again), over the sep axis
        if sep > 1:
            # K/V rotate at their true head count ONLY when the kv heads
            # divide the model axis; otherwise the implementation
            # repeats them to full q heads before the ring
            # (models/nlp/llama.py) — charge the repeated width there
            # or the planner under-costs (mp, sep) combos by up to
            # n_rep x
            kv_width = m.kv_width
            if mp > 1 and m.kv_heads and m.kv_heads % mp != 0:
                kv_width = m.q_width
            kv_tok = m.kv_bytes_per_token \
                or 2 * kv_width * m.bytes_per_param
            kv_chunk = batch_per_replica * (m.seq // sep) * kv_tok \
                / max(1, mp)  # heads split over mp shrink the local chunk
            sep_time = (m.n_layers / pp) * (sep - 1) * 3 \
                * comm_sep.p2p(kv_chunk)
        else:
            sep_time = 0.0

        # gradient allreduce of this rank's param shard: params are
        # replicated across BOTH dp and sep (sep shards activations by
        # sequence, not weights), so the sync ring spans dp*sep devices
        grad_bytes = m.n_params / (mp * pp) * 4  # f32 grads
        comm_grad = CommCost(c.ici, dp * sep)
        dp_time = comm_grad.all_reduce(grad_bytes) if dp * sep > 1 else 0.0

        # pipeline: bubble fraction + p2p per microbatch boundary
        bubble = (pp - 1) / (M + pp - 1) if pp > 1 else 0.0
        p2p_time = 2 * M * (pp - 1) * comm_pp.p2p(act_bytes) / max(1, pp) \
            if pp > 1 else 0.0

        total = (compute + tp_time + sep_time) / (1 - bubble) \
            + dp_time + p2p_time

        # memory per device: params + grads + adam moments + activations
        param_b = m.n_params / (mp * pp) * m.bytes_per_param
        opt_b = m.n_params / (mp * pp) * 8  # two f32 moments
        grad_b = m.n_params / (mp * pp) * 4
        act_b = (m.n_layers / pp) * batch_per_replica * (m.seq // sep) \
            * m.hidden * m.bytes_per_param * 4 / M  # remat'd working set
        mem = param_b + opt_b + grad_b + act_b
        return {"total": total, "compute": compute, "tp_comm": tp_time,
                "sep_comm": sep_time, "dp_comm": dp_time,
                "pp_p2p": p2p_time, "bubble": bubble,
                "memory_bytes": mem, "fits": mem < c.device.mem_bytes}
