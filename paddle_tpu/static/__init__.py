"""paddle_tpu.static — static-graph API.

~ python/paddle/static/ over the ProgramDesc/Executor stack (SURVEY.md §3.3,
layer 5). TPU-native: ops on symbolic ``static.data`` vars are captured as a
functional DAG (graph.py); ``Executor.run`` compiles the whole program —
forward, ``append_backward`` grads, ``Optimizer.minimize`` updates — into a
single ``jax.jit`` program per feed signature (executor.py). The
InterpreterCore/ParallelExecutor machinery collapses into the XLA scheduler.
"""
from __future__ import annotations

from ..jit import InputSpec  # noqa: F401
from .graph import (Program, StaticVar, GradVar, data, program_guard,  # noqa
                    default_main_program, default_startup_program,
                    append_backward, gradients, in_static_mode)
from .executor import (Executor, CompiledProgram, Scope, global_scope,  # noqa
                       scope_guard)
from .io import save_inference_model, load_inference_model  # noqa: F401
from . import nn  # noqa: F401
from .extras import (  # noqa: F401
    BuildStrategy, ExecutionStrategy, ExponentialMovingAverage,
    IpuCompiledProgram, IpuStrategy, ParallelExecutor, Print,
    WeightNormParamAttr, accuracy, auc, cpu_places, create_global_var,
    create_parameter, cuda_places, deserialize_persistables,
    deserialize_program, device_guard, ipu_shard_guard, load, load_from_file,
    load_program_state, mlu_places, normalize_program, npu_places, save,
    save_to_file, serialize_persistables, serialize_program,
    set_program_state, xpu_places,
)
from .nn import py_func  # noqa: F401

Variable = StaticVar

__all__ = [
    "Program", "StaticVar", "Variable", "GradVar", "data", "program_guard",
    "default_main_program", "default_startup_program", "append_backward",
    "gradients", "Executor", "CompiledProgram", "Scope", "global_scope",
    "scope_guard", "save_inference_model", "load_inference_model", "nn",
    "InputSpec",
]


def name_scope(prefix=None):
    import contextlib

    @contextlib.contextmanager
    def _g():
        yield
    return _g()

from . import passes  # noqa: F401,E402
from .passes import apply_pass, register_pass  # noqa: F401,E402
