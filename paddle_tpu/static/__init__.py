"""paddle_tpu.static — static-graph API surface.

The reference's ProgramDesc/Executor stack (SURVEY.md §3.3) has no TPU
analog: jax tracing + jit IS the static graph. This module keeps the
commonly-scripted entry points as thin adapters over paddle_tpu.jit so
static-style user code ports mechanically.
"""
from __future__ import annotations

from ..jit import InputSpec  # noqa: F401


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    raise NotImplementedError(
        "use paddle_tpu.jit.save(layer, path, input_spec=...) — the traced "
        "StableHLO + params artifact replaces save_inference_model")


def load_inference_model(path_prefix, executor=None, **kwargs):
    raise NotImplementedError(
        "use paddle_tpu.jit.load(path)")


class Executor:
    def __init__(self, place=None):
        raise NotImplementedError(
            "paddle_tpu has no Program/Executor; decorate your function "
            "with paddle_tpu.jit.to_static and call it directly")
