"""paddle.static namespace fillers: strategies, EMA, places, program state
serialization, host-print.

~ python/paddle/static/__init__.py re-exports backed by fluid/framework.py,
compiler.py, fluid/io.py. Program "serialization" here pickles the parameter
set (the graph itself is re-captured from Python — the TPU design has no
protobuf ProgramDesc; StableHLO export in static/io.py is the compiled-program
artifact)."""
from __future__ import annotations

import contextlib
import pickle

import numpy as np

from ..core.tensor import Parameter, Tensor
from . import graph as G


class BuildStrategy:
    """~ BuildStrategy (framework/details/build_strategy.h): graph-build
    knobs. XLA owns fusion/memory planning, so these are accepted and
    recorded; reduce_strategy etc. remain meaningful to the distributed
    wrappers that read them."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ""
        self.enable_sequential_execution = False
        self.fuse_broadcast_ops = False
        self.fuse_elewise_add_act_ops = False
        self.fuse_all_optimizer_ops = False
        self.memory_optimize = None
        self.enable_inplace = False
        self.build_cinn_pass = False
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    """~ ExecutionStrategy: executor scheduling knobs (XLA schedules)."""

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 100
        self.num_iteration_per_run = 1
        self.use_thread_barrier = True


class ParallelExecutor:
    """~ fluid.ParallelExecutor (framework/parallel_executor.h) — legacy
    multi-device wrapper. Maps onto the jit Executor: XLA + mesh sharding
    replace SSA-graph replication; kept for API compat."""

    def __init__(self, use_cuda=None, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None):
        from .executor import Executor
        self._program = main_program or G.default_main_program()
        self._exe = Executor()

    def run(self, fetch_list=None, feed=None, feed_dict=None,
            return_numpy=True):
        return self._exe.run(self._program, feed=feed or feed_dict,
                             fetch_list=fetch_list, return_numpy=return_numpy)


class CompiledProgramExtras:
    pass


class WeightNormParamAttr:
    """~ paddle.static.WeightNormParamAttr (fluid/param_attr.py)."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


class ExponentialMovingAverage:
    """~ paddle.static.ExponentialMovingAverage (fluid/optimizer.py:...):
    shadow = decay * shadow + (1 - decay) * param, with apply()/restore()
    context for eval-time parameter swapping."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._shadow = {}
        self._backup = {}
        self._step = 0

    def update(self, program=None):
        prog = program or G.default_main_program()
        self._step += 1
        decay = min(self._decay, (1 + self._step) / (10 + self._step))
        for p in prog.all_parameters():
            key = id(p)
            cur = np.asarray(p._value)
            if key not in self._shadow:
                self._shadow[key] = cur.copy()
            else:
                self._shadow[key] = (decay * self._shadow[key]
                                     + (1 - decay) * cur)

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        import jax.numpy as jnp
        prog = G.default_main_program()
        for p in prog.all_parameters():
            key = id(p)
            if key in self._shadow:
                self._backup[key] = p._value
                p._value = jnp.asarray(self._shadow[key])
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        prog = G.default_main_program()
        for p in prog.all_parameters():
            key = id(p)
            if key in self._backup:
                p._value = self._backup.pop(key)


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """~ paddle.static.Print (operators/print_op): identity + host print."""
    vals = np.asarray(input._value)
    head = message or "Var"
    parts = [head]
    if print_tensor_name:
        parts.append(f"name={input.name}")
    if print_tensor_shape:
        parts.append(f"shape={list(vals.shape)}")
    if print_tensor_type:
        parts.append(f"dtype={vals.dtype}")
    flat = vals.reshape(-1)[:summarize]
    parts.append(f"data={flat.tolist()}")
    print("  ".join(str(p) for p in parts))
    return input


# ---- metric helpers --------------------------------------------------------

def accuracy(input, label, k=1, correct=None, total=None):
    """~ paddle.static.accuracy (metrics.py)."""
    from ..ops.dispatch import apply_op
    import jax.numpy as jnp

    def fn(x, y):
        topk = jnp.argsort(-x, axis=-1)[..., :k]
        y = y.reshape(-1, 1)
        hit = jnp.any(topk == y, axis=-1)
        return jnp.mean(hit.astype(jnp.float32))
    return apply_op("accuracy", fn, input, label)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """~ paddle.static.auc — single-batch AUC (host; the reference
    accumulates stat tensors across batches, covered by metric.Auc)."""
    from ..metric import Auc as _Auc
    m = _Auc(num_thresholds=num_thresholds)
    m.update(np.asarray(input._value), np.asarray(label._value))
    val = m.accumulate()
    return (Tensor(np.float32(val)), Tensor(np.float32(val)),
            Tensor(np.zeros(1)), Tensor(np.zeros(1)), Tensor(np.zeros(1)),
            Tensor(np.zeros(1)))


# ---- places ----------------------------------------------------------------

def cpu_places(device_count=None):
    from ..core.place import CPUPlace
    import os
    n = device_count or int(os.environ.get("CPU_NUM", 1))
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    from ..core.place import CUDAPlace, device_count as _dc
    ids = device_ids if device_ids is not None else range(_dc())
    return [CUDAPlace(i) for i in ids]


def npu_places(device_ids=None):
    from ..core.place import NPUPlace, device_count as _dc
    ids = device_ids if device_ids is not None else range(_dc())
    return [NPUPlace(i) for i in ids]


def xpu_places(device_ids=None):
    from ..core.place import XPUPlace, device_count as _dc
    ids = device_ids if device_ids is not None else range(_dc())
    return [XPUPlace(i) for i in ids]


def mlu_places(device_ids=None):
    return npu_places(device_ids)


# ---- global vars / parameters ----------------------------------------------

def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    import jax.numpy as jnp
    from ..core import dtype as dtypes
    v = Parameter(jnp.full([int(s) for s in shape], value,
                           dtypes.convert_dtype(dtype)))
    v.persistable = persistable
    if name:
        v.name = name
    v.stop_gradient = True
    G.default_main_program()._add_param(v)
    return v


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..ops.misc import create_parameter as _cp
    p = _cp(shape, dtype, name=name, attr=attr,
            default_initializer=default_initializer)
    G.default_main_program()._add_param(p)
    return p


# ---- guards ----------------------------------------------------------------

@contextlib.contextmanager
def device_guard(device=None):
    """~ paddle.static.device_guard: pins ops to a device. XLA handles
    placement inside one program; host pinning maps to jax.default_device."""
    import jax
    if device in (None, "cpu"):
        dev = jax.devices("cpu")[0] if device == "cpu" else None
    else:
        dev = jax.devices()[0]
    if dev is None:
        yield
    else:
        with jax.default_device(dev):
            yield


@contextlib.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    yield


class IpuStrategy:
    """Capability slot for the reference's Graphcore backend
    (python/paddle/fluid/compiler.py IpuStrategy) — config container only;
    this framework's accelerator is the TPU."""

    def __init__(self):
        self.num_ipus = 1
        self.is_training = True
        self.micro_batch_size = 1
        self.enable_manual_shard = False

    def set_graph_config(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)

    def set_pipelining_config(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


class IpuCompiledProgram:
    def __init__(self, program=None, scope=None, ipu_strategy=None):
        raise RuntimeError(
            "IpuCompiledProgram targets Graphcore IPUs; this framework "
            "compiles for TPU via static.CompiledProgram / jax.jit")


# ---- program state io ------------------------------------------------------

def _program_state(program=None):
    prog = program or G.default_main_program()
    state = {}
    for i, p in enumerate(prog.all_parameters()):
        state[p.name or f"param_{i}"] = np.asarray(p._value)
    return state


def load_program_state(model_path, var_list=None):
    with open(model_path + ".pdparams" if not model_path.endswith(".pdparams")
              else model_path, "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict):
    import jax.numpy as jnp
    prog = program or G.default_main_program()
    for i, p in enumerate(prog.all_parameters()):
        key = p.name or f"param_{i}"
        if key in state_dict:
            p._value = jnp.asarray(state_dict[key])
    return program


def save(program, model_path, protocol=4):
    """~ paddle.static.save — persist program parameters (+ a manifest)."""
    state = _program_state(program)
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)
    with open(model_path + ".pdmodel", "wb") as f:
        pickle.dump({"n_params": len(state), "names": list(state)}, f)


def load(program, model_path, executor=None, var_list=None):
    state = load_program_state(model_path)
    set_program_state(program, state)


def serialize_program(feed_vars=None, fetch_vars=None, program=None):
    prog = program or G.default_main_program()
    return pickle.dumps({"names": [p.name for p in prog.all_parameters()],
                         "datas": list(prog._datas)})


def serialize_persistables(feed_vars=None, fetch_vars=None, program=None):
    return pickle.dumps(_program_state(program))


def deserialize_program(data):
    return pickle.loads(data)


def deserialize_persistables(program, data, executor=None):
    set_program_state(program, pickle.loads(data))
    return program


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars):
    """~ paddle.static.normalize_program — prune to the feed->fetch
    subgraph; our Program is already the captured minimal DAG, so this
    returns an inference clone."""
    return program.clone(for_test=True)
