"""static.save/load_inference_model over jax.export.

~ python/paddle/static/io.py (save_inference_model → pruned frozen program
+ params; fluid/io.cc). TPU-native artifact: the captured DAG is pruned to
feed→fetch, parameters are frozen in as constants, and the result is
serialized with jax.export (same .pdexport contract as paddle_tpu.jit.save)
— loadable by paddle_tpu.jit.load / inference.Predictor.
"""
from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from . import graph as G
from .executor import _eval_var


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) \
        else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    prog = program if program is not None else G.default_main_program()
    params = list(prog._params)
    param_vals = [p._value for p in params]

    def frozen(*feed_arrays):
        env = {}
        for dv, v in zip(feed_vars, feed_arrays):
            env[id(dv)] = v
        for p, v in zip(params, param_vals):
            env[id(p)] = v
        return tuple(_eval_var(f, env) for f in fetch_vars)

    # Shape polymorphism: -1 feed dims export as symbolic dimensions
    # shared per dim-position — one artifact serves any batch size
    # (shared contract with paddle_tpu.jit.save).
    from ..jit import symbolic_export
    exp = symbolic_export(
        frozen, [(dv.shape, dv._jdtype) for dv in feed_vars],
        warn_prefix="save_inference_model")

    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "w") as f:
        f.write(str(exp.mlir_module()))
    with open(path_prefix + ".pdexport", "wb") as f:
        f.write(exp.serialize())
    state = {p.name: np.asarray(v) for p, v in zip(params, param_vals)}
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump(state, f, protocol=4)
    with open(path_prefix + ".pdmeta", "wb") as f:
        pickle.dump({"class": "StaticProgram", "has_model": True,
                     "has_export": True,
                     "feed_names": [v.name for v in feed_vars],
                     "fetch_names": [v.name for v in fetch_vars]}, f)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns (program_like, feed_names, fetch_names); the program_like is
    a TranslatedLayer callable on feed arrays (the NaiveExecutor role)."""
    from ..jit import load as jit_load
    layer = jit_load(path_prefix)
    meta = {}
    if os.path.exists(path_prefix + ".pdmeta"):
        with open(path_prefix + ".pdmeta", "rb") as f:
            meta = pickle.load(f)
    return (layer, meta.get("feed_names", []), meta.get("fetch_names", []))
