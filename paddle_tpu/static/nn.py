"""paddle.static.nn — program-building layer functions.

~ python/paddle/static/nn/common.py (fc, conv2d, batch_norm, embedding...):
each call creates fresh parameters in the default main program (the
reference's LayerHelper.create_parameter) by instantiating the eager nn
layer and calling it on the symbolic input; the layer object is parked on
the program so its Parameters stay alive.
"""
from __future__ import annotations

import numpy as np

from . import graph as G


def _park(layer):
    G.default_main_program()._layers.append(layer)
    return layer


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """~ static.nn.fc: flattens trailing dims, affine, optional act."""
    from .. import nn
    from ..nn import functional as F
    from ..ops import manipulation as M

    in_shape = x.shape
    in_features = int(np.prod(in_shape[num_flatten_dims:]))
    layer = _park(nn.Linear(in_features, size,
                            weight_attr=weight_attr, bias_attr=bias_attr))
    h = x
    if len(in_shape) > num_flatten_dims + 1:
        lead = list(in_shape[:num_flatten_dims])
        lead = [(-1 if d == -1 else d) for d in lead]
        h = M.reshape(h, lead + [in_features])
    out = layer(h)
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    """~ static.nn.embedding."""
    from .. import nn
    layer = _park(nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                               weight_attr=param_attr))
    return layer(input)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False,
               name=None, **kwargs):
    """~ static.nn.batch_norm. Running stats stay frozen inside the compiled
    program (batch stats are used in training mode)."""
    from .. import nn
    from ..nn import functional as F
    nc = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = _park(nn.BatchNorm2D(nc, momentum=momentum, epsilon=epsilon,
                                 data_format=data_layout)
                  if input.ndim == 4 else
                  nn.BatchNorm1D(nc, momentum=momentum, epsilon=epsilon))
    if is_test:
        layer.eval()
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    """~ static.nn.conv2d."""
    from .. import nn
    from ..nn import functional as F
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    layer = _park(nn.Conv2D(in_ch, num_filters, filter_size, stride=stride,
                            padding=padding, dilation=dilation, groups=groups,
                            weight_attr=param_attr, bias_attr=bias_attr,
                            data_format=data_format))
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out
