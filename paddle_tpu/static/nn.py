"""paddle.static.nn — program-building layer functions.

~ python/paddle/static/nn/common.py (fc, conv2d, batch_norm, embedding...):
each call creates fresh parameters in the default main program (the
reference's LayerHelper.create_parameter) by instantiating the eager nn
layer and calling it on the symbolic input; the layer object is parked on
the program so its Parameters stay alive.
"""
from __future__ import annotations

import numpy as np

from . import graph as G


def _park(layer):
    G.default_main_program()._layers.append(layer)
    return layer


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """~ static.nn.fc: flattens trailing dims, affine, optional act."""
    from .. import nn
    from ..nn import functional as F
    from ..ops import manipulation as M

    in_shape = x.shape
    in_features = int(np.prod(in_shape[num_flatten_dims:]))
    layer = _park(nn.Linear(in_features, size,
                            weight_attr=weight_attr, bias_attr=bias_attr))
    h = x
    if len(in_shape) > num_flatten_dims + 1:
        lead = list(in_shape[:num_flatten_dims])
        lead = [(-1 if d == -1 else d) for d in lead]
        h = M.reshape(h, lead + [in_features])
    out = layer(h)
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    """~ static.nn.embedding."""
    from .. import nn
    layer = _park(nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                               weight_attr=param_attr))
    return layer(input)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False,
               name=None, **kwargs):
    """~ static.nn.batch_norm. Running stats stay frozen inside the compiled
    program (batch stats are used in training mode)."""
    from .. import nn
    from ..nn import functional as F
    nc = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = _park(nn.BatchNorm2D(nc, momentum=momentum, epsilon=epsilon,
                                 data_format=data_layout)
                  if input.ndim == 4 else
                  nn.BatchNorm1D(nc, momentum=momentum, epsilon=epsilon))
    if is_test:
        layer.eval()
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    """~ static.nn.conv2d."""
    from .. import nn
    from ..nn import functional as F
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    layer = _park(nn.Conv2D(in_ch, num_filters, filter_size, stride=stride,
                            padding=padding, dilation=dilation, groups=groups,
                            weight_attr=param_attr, bias_attr=bias_attr,
                            data_format=data_format))
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


# ---- conv / norm family ----------------------------------------------------

def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None,
                     data_format="NCHW", name=None):
    from .. import nn
    from ..nn import functional as F
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    if filter_size is None:
        # derive kernel from requested output size (reference behavior)
        osz = (output_size, output_size) if isinstance(output_size, int) \
            else tuple(output_size)
        st = (stride, stride) if isinstance(stride, int) else tuple(stride)
        pd = (padding, padding) if isinstance(padding, int) \
            else tuple(padding)
        in_sp = input.shape[2:] if data_format == "NCHW" else input.shape[1:3]
        filter_size = tuple(
            osz[i] - (in_sp[i] - 1) * st[i] + 2 * pd[i] for i in range(2))
    layer = _park(nn.Conv2DTranspose(
        in_ch, num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, groups=groups, weight_attr=param_attr,
        bias_attr=bias_attr, data_format=data_format))
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCDHW", name=None):
    from .. import nn
    from ..nn import functional as F
    in_ch = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    layer = _park(nn.Conv3D(in_ch, num_filters, filter_size, stride=stride,
                            padding=padding, dilation=dilation, groups=groups,
                            weight_attr=param_attr, bias_attr=bias_attr,
                            data_format=data_format))
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None,
                     data_format="NCDHW", name=None):
    from .. import nn
    from ..nn import functional as F
    in_ch = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    layer = _park(nn.Conv3DTranspose(
        in_ch, num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, groups=groups, weight_attr=param_attr,
        bias_attr=bias_attr, data_format=data_format))
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    from .. import nn
    from ..nn import functional as F
    shape = [int(s) for s in input.shape[begin_norm_axis:]]
    layer = _park(nn.LayerNorm(shape, epsilon=epsilon))
    if not scale:
        layer.weight = None
    if not shift:
        layer.bias = None
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    from .. import nn
    from ..nn import functional as F
    nc = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = _park(nn.GroupNorm(groups, nc, epsilon=epsilon))
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from .. import nn
    nc = input.shape[1]
    cls = {3: nn.InstanceNorm1D, 4: nn.InstanceNorm2D,
           5: nn.InstanceNorm3D}[input.ndim]
    return _park(cls(nc, epsilon=epsilon))(input)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              enable_scale_and_shift=False, **kwargs):
    """~ static.nn.data_norm: normalization by accumulated batch statistics
    (PS-style CTR models). Single-program form: batch statistics."""
    from ..ops.dispatch import apply_op
    import jax.numpy as jnp

    def fn(x):
        mean = jnp.mean(x, axis=0, keepdims=True)
        var = jnp.mean(jnp.square(x - mean), axis=0, keepdims=True)
        return (x - mean) / jnp.sqrt(var + epsilon)
    out = apply_op("data_norm", fn, input)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from ..nn.layer.norm import spectral_normalize
    return spectral_normalize(weight, dim=dim, power_iters=power_iters,
                              eps=eps)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    from ..core.tensor import Parameter
    from ..ops import activation as A
    import jax.numpy as jnp
    if mode == "all":
        n = 1
    elif mode == "channel":
        n = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    else:
        n = int(np.prod(x.shape[1:]))
    alpha = Parameter(jnp.full((n,), 0.25, jnp.float32))
    G.default_main_program()._layers.append(alpha)
    if mode == "channel" and data_format == "NCHW":
        from ..ops.manipulation import reshape
        a = reshape(alpha, [1, n] + [1] * (x.ndim - 2))
    else:
        a = alpha
    return A.prelu(x, a)


def bilinear_tensor_product(x, y, size, act=None, param_attr=None,
                            bias_attr=None, name=None):
    from .. import nn
    layer = _park(nn.Bilinear(x.shape[-1], y.shape[-1], size))
    out = layer(x, y)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def deform_conv2d(input, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    from ..vision.ops import DeformConv2D
    layer = _park(DeformConv2D(input.shape[1], num_filters, filter_size,
                               stride, padding, dilation, deformable_groups,
                               groups, param_attr, bias_attr))
    return layer(input, offset, mask)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """~ static.nn.row_conv (lookahead conv, Deep Speech): each step mixes
    the next ``future_context_size`` steps per feature channel."""
    from ..core.tensor import Parameter
    from ..ops.dispatch import apply_op
    import jax
    import jax.numpy as jnp
    d = input.shape[-1]
    k = future_context_size + 1
    w = Parameter(jnp.full((k, d), 1.0 / k, jnp.float32))
    G.default_main_program()._layers.append(w)

    def fn(x, wv):
        # x: (B, T, D)
        xp = jnp.pad(x, [(0, 0), (0, k - 1), (0, 0)])
        out = jnp.zeros_like(x)
        for i in range(k):
            out = out + xp[:, i:i + x.shape[1]] * wv[i]
        return out
    out = apply_op("row_conv", fn, input, w)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def crf_decoding(input, param_attr=None, label=None, length=None,
                 transition=None):
    """~ static.nn.crf_decoding — viterbi path over emissions. The
    transition matrix is the learned CRF parameter (created here when not
    passed, like the reference's LayerHelper parameter)."""
    from ..core.tensor import Parameter
    from ..text import viterbi_decode
    import jax.numpy as jnp
    n = input.shape[-1]
    if transition is None:
        transition = Parameter(jnp.zeros((n, n), jnp.float32))
        G.default_main_program()._layers.append(transition)
    emis = input if input.ndim == 3 else input[None]
    scores, path = viterbi_decode(emis, transition, lengths=length)
    return path


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=5, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """~ static.nn.nce (noise-contrastive estimation, operators/nce_op):
    logistic loss on the true class + sampled negatives."""
    from ..core.tensor import Parameter
    from ..core.generator import default_generator
    from ..ops.dispatch import apply_op
    import jax
    import jax.numpy as jnp
    d = input.shape[-1]
    w = Parameter(jax.random.normal(default_generator().next_key(),
                                    (num_total_classes, d)) * 0.01)
    b = Parameter(jnp.zeros((num_total_classes,)))
    G.default_main_program()._layers.extend([w, b])
    neg = jax.random.randint(default_generator().next_key(),
                             (num_neg_samples,), 0, num_total_classes)

    def fn(x, lab, wv, bv):
        lab = lab.reshape(-1)
        pos_logit = jnp.sum(x * wv[lab], -1) + bv[lab]
        neg_logit = x @ wv[neg].T + bv[neg]        # (B, S)

        def softplus(z):
            return jnp.maximum(z, 0) + jnp.log1p(jnp.exp(-jnp.abs(z)))
        loss = softplus(-pos_logit) + jnp.sum(softplus(neg_logit), -1)
        return loss[:, None]
    return apply_op("nce", fn, input, label, w, b)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, param_attr=None, dtype="float32"):
    """~ static.nn.sparse_embedding — the PS-backed large-table embedding
    slot; single-host form is a dense table (the distributed table lives in
    distributed.ps)."""
    return embedding(input, size, is_sparse=True, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    """~ static.nn.py_func (operators/py_func_op): host-python op. Eager
    semantics: call through (jax.pure_callback inside jit programs)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    return func(*xs)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, offset=0.5, flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """~ static.nn.multi_box_head (SSD detection head,
    python/paddle/fluid/layers/detection.py): per-feature-map loc/conf conv
    heads + prior boxes."""
    from .. import nn
    from ..ops.manipulation import concat, reshape, transpose
    from ..core.tensor import Tensor
    import jax.numpy as jnp
    n_layers = len(inputs)
    if min_sizes is None:
        min_ratio, max_ratio = int(min_ratio), int(max_ratio)
        step = int((max_ratio - min_ratio) / max(1, n_layers - 2))
        min_sizes, max_sizes = [], []
        for r in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * r / 100.0)
            max_sizes.append(base_size * (r + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes[:n_layers - 1]
        max_sizes = [base_size * 0.2] + max_sizes[:n_layers - 1]
    locs, confs, priors, vars_ = [], [], [], []
    img_h = image.shape[2]
    img_w = image.shape[3]
    for i, feat in enumerate(inputs):
        ars = aspect_ratios[i] if isinstance(aspect_ratios[i],
                                             (list, tuple)) \
            else [aspect_ratios[i]]
        n_prior = len([a for a in ars if a != 1]) * (2 if flip else 1) + 2
        h, w = feat.shape[2], feat.shape[3]
        loc_head = _park(nn.Conv2D(feat.shape[1], n_prior * 4, kernel_size,
                                   padding=pad, stride=stride))
        conf_head = _park(nn.Conv2D(feat.shape[1], n_prior * num_classes,
                                    kernel_size, padding=pad, stride=stride))
        loc = loc_head(feat)
        conf = conf_head(feat)
        locs.append(reshape(transpose(loc, [0, 2, 3, 1]),
                            [loc.shape[0], -1, 4]))
        confs.append(reshape(transpose(conf, [0, 2, 3, 1]),
                             [conf.shape[0], -1, num_classes]))
        step_w = steps[i] if steps else img_w / w
        step_h = steps[i] if steps else img_h / h
        cx = (np.arange(w) + offset) * step_w / img_w
        cy = (np.arange(h) + offset) * step_h / img_h
        cxg, cyg = np.meshgrid(cx, cy)
        smin = min_sizes[i] / base_size
        smax = (max_sizes[i] / base_size) if max_sizes else smin
        sizes = [(smin, smin), (float(np.sqrt(smin * smax)),) * 2]
        for ar in ars:
            if ar == 1:
                continue
            sizes.append((smin * np.sqrt(ar), smin / np.sqrt(ar)))
            if flip:
                sizes.append((smin / np.sqrt(ar), smin * np.sqrt(ar)))
        boxes = []
        for (sw, sh) in sizes[:n_prior]:
            boxes.append(np.stack([cxg - sw / 2, cyg - sh / 2,
                                   cxg + sw / 2, cyg + sh / 2], -1))
        pb = np.stack(boxes, 2).reshape(-1, 4).astype(np.float32)
        if clip:
            pb = pb.clip(0, 1)
        priors.append(pb)
        vars_.append(np.tile(np.array([0.1, 0.1, 0.2, 0.2], np.float32),
                             (len(pb), 1)))
    mbox_locs = concat(locs, axis=1)
    mbox_confs = concat(confs, axis=1)
    box = Tensor(jnp.asarray(np.concatenate(priors)))
    var = Tensor(jnp.asarray(np.concatenate(vars_)))
    return mbox_locs, mbox_confs, box, var


# ---- sequence ops ----------------------------------------------------------
# The reference's sequence_* ops act on LoD (ragged) tensors
# (paddle/fluid/operators/sequence_ops/). TPU-native representation: padded
# dense (B, T, ...) plus an optional lengths vector — the static-shape form
# XLA requires; lengths default to full T.

def _seq_mask(x, length):
    import jax.numpy as jnp
    B, T = x.shape[0], x.shape[1]
    if length is None:
        return jnp.ones((B, T), bool)
    lv = length._value if hasattr(length, "_value") else jnp.asarray(length)
    return jnp.arange(T)[None, :] < lv[:, None]


def sequence_pool(input, pool_type="sum", length=None, pad_value=0.0):
    from ..ops.dispatch import apply_op
    import jax.numpy as jnp

    def fn(x, *rest):
        m = _seq_mask(x, rest[0] if rest else None)
        mf = m.astype(x.dtype)
        while mf.ndim < x.ndim:
            mf = mf[..., None]
        pt = pool_type.lower()
        if pt == "sum":
            return jnp.sum(x * mf, 1)
        if pt in ("average", "mean"):
            return jnp.sum(x * mf, 1) / jnp.maximum(mf.sum(1), 1.0)
        if pt == "sqrt":
            return jnp.sum(x * mf, 1) / jnp.sqrt(jnp.maximum(mf.sum(1), 1.0))
        if pt == "max":
            neg = jnp.finfo(x.dtype).min
            return jnp.max(jnp.where(mf > 0, x, neg), 1)
        if pt == "last":
            if rest:
                idx = jnp.clip(rest[0].astype(jnp.int32) - 1, 0, None)
            else:
                idx = jnp.full((x.shape[0],), x.shape[1] - 1, jnp.int32)
            sel = idx.reshape(-1, *([1] * (x.ndim - 1)))
            return jnp.take_along_axis(
                x, jnp.broadcast_to(sel, (x.shape[0], 1) + x.shape[2:]),
                1)[:, 0]
        if pt == "first":
            return x[:, 0]
        raise ValueError(f"unknown pool_type {pool_type}")
    args = [input] + ([length] if length is not None else [])
    return apply_op("sequence_pool", fn, *args)


def sequence_first_step(input, length=None):
    return sequence_pool(input, "first", length)


def sequence_last_step(input, length=None):
    return sequence_pool(input, "last", length)


def sequence_softmax(input, length=None):
    from ..ops.dispatch import apply_op
    import jax
    import jax.numpy as jnp

    def fn(x, *rest):
        m = _seq_mask(x, rest[0] if rest else None)
        while m.ndim < x.ndim:
            m = m[..., None]
        neg = jnp.finfo(x.dtype).min
        return jax.nn.softmax(jnp.where(m, x, neg), axis=1)
    args = [input] + ([length] if length is not None else [])
    return apply_op("sequence_softmax", fn, *args)


def sequence_reverse(x, length=None, name=None):
    from ..ops.dispatch import apply_op
    import jax.numpy as jnp

    def fn(v, *rest):
        if not rest:
            return jnp.flip(v, 1)
        lv = rest[0].astype(jnp.int32)
        T = v.shape[1]
        idx = jnp.arange(T)[None, :]
        rev = jnp.where(idx < lv[:, None], lv[:, None] - 1 - idx, idx)
        sel = rev.reshape(rev.shape + (1,) * (v.ndim - 2))
        sel = jnp.broadcast_to(sel, v.shape)
        return jnp.take_along_axis(v, sel, 1)
    args = [x] + ([length] if length is not None else [])
    return apply_op("sequence_reverse", fn, *args)


def sequence_concat(input, name=None):
    from ..ops.manipulation import concat
    return concat(list(input), axis=1)


def sequence_expand(x, y, ref_level=-1, name=None):
    """Repeat each row of x to y's time length (padded-form expand)."""
    from ..ops.dispatch import apply_op
    import jax.numpy as jnp

    def fn(xv, yv):
        reps = yv.shape[1]
        return jnp.repeat(xv[:, None], reps, 1) if xv.ndim == 2 \
            else jnp.repeat(xv, reps // xv.shape[1], 1)
    return apply_op("sequence_expand", fn, x, y)


def sequence_expand_as(x, y, name=None):
    return sequence_expand(x, y)


def sequence_pad(x, pad_value, maxlen=None, name=None):
    """(B, T, ...) dense input; pads/trims to maxlen, returns
    (padded, lengths) like the reference."""
    from ..ops.dispatch import apply_op
    from ..core.tensor import Tensor
    import jax.numpy as jnp

    def fn(v, pv):
        T = v.shape[1]
        target = maxlen or T
        if target > T:
            return jnp.pad(v, [(0, 0), (0, target - T)]
                           + [(0, 0)] * (v.ndim - 2))
        return v[:, :target]
    padded = apply_op("sequence_pad", fn, x, pad_value)
    lengths = Tensor(jnp.full((x.shape[0],),
                              min(x.shape[1], maxlen or x.shape[1]),
                              jnp.int32))
    return padded, lengths


def sequence_unpad(x, length, name=None):
    """Trim to max(length) (static-shape trim; per-row raggedness remains
    masked)."""
    from ..ops.dispatch import apply_op
    import numpy as _np
    lv = length.numpy() if hasattr(length, "numpy") else _np.asarray(length)
    tmax = int(lv.max()) if lv.size else 0
    return apply_op("sequence_unpad", lambda v, l: v[:, :tmax], x, length)


def sequence_reshape(input, new_dim):
    from ..ops.manipulation import reshape
    return reshape(input, [input.shape[0], -1, new_dim])


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    from ..ops.dispatch import apply_op
    import jax.numpy as jnp

    def fn(v):
        T = v.shape[1]
        vp = jnp.pad(v, [(0, 0), (0, win_size - 1)],
                     constant_values=pad_value)
        return jnp.stack([vp[:, i:i + T] for i in range(win_size)], -1)
    return apply_op("sequence_enumerate", fn, input, nondiff=True)


def sequence_slice(input, offset, length, name=None):
    from ..ops.dispatch import apply_op
    import jax.numpy as jnp

    def fn(v, off, ln):
        T = v.shape[1]
        idx = off.reshape(-1, 1).astype(jnp.int32) + jnp.arange(T)[None]
        m = jnp.arange(T)[None] < ln.reshape(-1, 1)
        idx = jnp.clip(idx, 0, T - 1)
        sel = idx.reshape(idx.shape + (1,) * (v.ndim - 2))
        g = jnp.take_along_axis(
            v, jnp.broadcast_to(sel, (v.shape[0], T) + v.shape[2:]), 1)
        mf = m.astype(v.dtype)
        while mf.ndim < g.ndim:
            mf = mf[..., None]
        return g * mf
    return apply_op("sequence_slice", fn, input, offset, length)


def sequence_scatter(input, index, updates, name=None):
    from ..ops.dispatch import apply_op
    import jax.numpy as jnp

    def fn(v, idx, upd):
        B = v.shape[0]
        b = jnp.repeat(jnp.arange(B)[:, None], idx.shape[1], 1)
        return v.at[b, idx].add(upd)
    return apply_op("sequence_scatter", fn, input, index, updates)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, act=None,
                  param_attr=None, bias_attr=None, name=None):
    """~ static.nn.sequence_conv: 1D context window conv over time."""
    from ..core.tensor import Parameter
    from ..core.generator import default_generator
    from ..ops.dispatch import apply_op
    import jax
    import jax.numpy as jnp
    d = input.shape[-1]
    limit = float(np.sqrt(6.0 / (filter_size * d + num_filters)))
    w = Parameter(jax.random.uniform(default_generator().next_key(),
                                     (filter_size * d, num_filters),
                                     jnp.float32, -limit, limit))
    b = Parameter(jnp.zeros((num_filters,))) if bias_attr is not False \
        else None
    G.default_main_program()._layers.extend([w] + ([b] if b is not None
                                                   else []))
    start = padding_start if padding_start is not None \
        else -(filter_size // 2)

    def fn(x, wv, *rest):
        B, T, D = x.shape
        cols = []
        for k in range(filter_size):
            shift = start + k
            if shift < 0:
                xs = jnp.pad(x, [(0, 0), (-shift, 0), (0, 0)])[:, :T]
            elif shift > 0:
                xs = jnp.pad(x, [(0, 0), (0, shift), (0, 0)])[:, shift:]
            else:
                xs = x
            cols.append(xs)
        col = jnp.concatenate(cols, -1)  # (B, T, k*D)
        out = col @ wv
        if rest:
            out = out + rest[0]
        return out
    args = [input, w] + ([b] if b is not None else [])
    out = apply_op("sequence_conv", fn, *args)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def sequence_erase(input, tokens, length=None, name=None):
    """Remove every occurrence of ``tokens`` from each row, compacting
    left. ~ sequence_erase_op.h (LoD shrink) in the padded+lengths form:
    returns (erased (B, T) with trailing pad 0, new_lengths (B,)).
    jit-able: the compaction is a stable argsort over the keep mask."""
    from ..core.tensor import Tensor
    from ..ops.dispatch import apply_op
    import jax.numpy as jnp

    tok = [int(t) for t in (tokens if hasattr(tokens, "__len__")
                            else [tokens])]

    def fn(v, *rest):
        keep = _seq_mask(v, rest[0] if rest else None)
        for t in tok:
            keep = keep & (v != t)
        # stable compaction: kept entries first, original order preserved
        order = jnp.argsort(jnp.where(keep, 0, 1), axis=1, stable=True)
        compacted = jnp.take_along_axis(v, order, 1)
        kept_sorted = jnp.take_along_axis(keep, order, 1)
        return (jnp.where(kept_sorted, compacted, 0),
                keep.sum(1).astype(jnp.int32))

    args = [input] + ([length] if length is not None else [])
    out, new_len = apply_op("sequence_erase", fn, *args, nondiff=True)
    return out, new_len


def sequence_topk_avg_pooling(input, topks, channel_num=None, row=None,
                              col=None, name=None):
    """Per-row top-k column averages per channel.
    ~ sequence_topk_avg_pooling_op.h (text-matching TopKPooling): input
    (B, C, R, L); for each (b, c, r) take the top-k values over the L
    (column) axis for every k in ``topks`` and average the REAL hits
    (rows shorter than k average what exists — the reference pads
    positions with TopKPosPaddingId and skips them). Returns
    (B, R, C * len(topks)); ``col`` (B,) masks valid columns.
    """
    from ..ops.dispatch import apply_op
    import jax
    import jax.numpy as jnp

    ks = [int(k) for k in topks]
    kmax = max(ks)

    def fn(x, *rest):
        B, C, R, L = x.shape
        if rest:
            cm = (jnp.arange(L)[None, :]
                  < rest[0].astype(jnp.int32)[:, None])  # (B, L)
            valid = cm[:, None, None, :]
        else:
            valid = jnp.ones((B, 1, 1, L), bool)
        neg = jnp.finfo(x.dtype).min
        masked = jnp.where(valid, x, neg)
        kk = min(kmax, L)
        top, _ = jax.lax.top_k(masked, kk)          # (B, C, R, kk)
        n_valid = jnp.broadcast_to(valid, x.shape).sum(-1)  # (B, C, R)
        outs = []
        for k in ks:
            kcl = min(k, kk)
            hit = jnp.minimum(n_valid, kcl)
            take = (jnp.arange(kk)[None, None, None, :] < hit[..., None])
            s = jnp.where(take, top[..., :kk], 0.0).sum(-1)
            outs.append(s / jnp.maximum(hit, 1).astype(x.dtype))
        out = jnp.stack(outs, -1)                   # (B, C, R, K)
        return out.transpose(0, 2, 1, 3).reshape(B, R, -1)

    args = [input] + ([col] if col is not None else [])
    return apply_op("sequence_topk_avg_pooling", fn, *args)


# ---- control flow re-exports ----------------------------------------------
from ..ops.control_flow import case, cond, switch_case, while_loop  # noqa
