"""Static-graph Executor: compose the captured DAG and jit it.

~ the reference's executor stack (SURVEY.md §3.3): python Executor
(fluid/executor.py:1103 run → _run_impl:1301) over StandaloneExecutor/
InterpreterCore (framework/new_executor/interpretercore.cc). Here the
"instruction list build" is a functional composition of the captured DAG
into one f(feeds, params) and the async dependency-driven dispatch is XLA's
scheduler: the whole program — forward, grads, optimizer update — compiles
to a single donated-state device program per feed signature (the fusion
InterpreterCore could never do).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor
from . import graph as G


class Scope:
    """~ framework/scope.h seen from python: name -> value view over the
    program's persistables."""

    def __init__(self):
        self._extra: Dict[str, np.ndarray] = {}

    def find_var(self, name):
        prog = G.default_main_program()
        try:
            v = prog.var(name)
        except KeyError:
            return self._extra.get(name)
        return v

    def var(self, name):
        return self.find_var(name)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def _g():
        yield scope
    return _g()


def _eval_var(var, env):
    """Recursively evaluate a StaticVar under the value environment.
    env maps id(var-or-param) -> traced jax value."""
    key = id(var)
    if key in env:
        return env[key]
    const = getattr(var, "_const_value", None)
    if const is not None:
        # stamped by static.passes constant_folding: feeds never reach this
        # var, its value is known ahead of trace
        env[key] = const
        return const
    node = var._node
    if node is None:
        raise RuntimeError(
            f"StaticVar '{var.name}' was not fed (feed slots present: "
            "check the feed dict keys against static.data names)")
    vals = []
    for a in node.args:
        if G._is_symbolic(a):
            vals.append(_eval_var(a, env))
        elif isinstance(a, Tensor):
            vals.append(env.get(id(a), a._value))
        else:
            vals.append(a)
    out = node.fn(*vals, **node.kwargs)
    outs = (out,) if node.single else tuple(out)
    for v, o in zip(node.out_vars, outs):
        env[id(v)] = o
    return env[key]


class CompiledProgram:
    """~ fluid.CompiledProgram/compiler.py — the jit happens inside
    Executor.run regardless, so this is a strategy-carrying view."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy

    def __getattr__(self, name):
        return getattr(self._program, name)


class Executor:
    """~ paddle.static.Executor. place is accepted for API parity; XLA owns
    placement (the default device)."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[tuple, callable] = {}

    # ------------------------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None,
            feed_var_name="feed", fetch_var_name="fetch", scope=None,
            return_numpy=True, use_prune=False):
        prog = program if program is not None else G.default_main_program()
        if isinstance(prog, CompiledProgram):
            prog = prog._program
        feed = feed or {}
        fetch_list = fetch_list or []

        # startup program: snapshot/restore parameter init values
        if prog._n_ops == 0 and not prog._opts and not fetch_list:
            self._run_startup(prog)
            return []

        fetch_vars = [self._resolve_fetch(prog, f) for f in fetch_list]
        feed_items = sorted(feed.items())
        feed_names, feed_vals = [], []
        for name, v in feed_items:
            dv = prog._datas.get(name)
            if dv is None:
                raise KeyError(
                    f"feed key {name!r} does not match any static.data var "
                    f"(have {list(prog._datas)})")
            arr = v._value if isinstance(v, Tensor) else jnp.asarray(v)
            feed_names.append(name)
            feed_vals.append(jnp.asarray(arr, dv._jdtype))

        key = (prog.id, prog._version,
               tuple(feed_names),
               tuple((tuple(v.shape), str(v.dtype)) for v in feed_vals),
               tuple(id(f) for f in fetch_vars))
        step_fn = self._cache.get(key)
        if step_fn is None:
            step_fn = self._build(prog, feed_names, fetch_vars)
            self._cache[key] = step_fn

        params = list(prog._params)
        param_vals = [p._value for p in params]
        opt_states, lrs, steps = [], [], []
        for optimizer, _loss, opt_params in prog._opts:
            ps = self._opt_params(prog, optimizer, opt_params)
            opt_states.append([optimizer._accs_for(p) for p in ps])
            lrs.append(jnp.asarray(optimizer.get_lr(), jnp.float32))
            steps.append(jnp.asarray(optimizer._step_count + 1, jnp.int32))
        self._shard_opt_states(prog, opt_states)

        fetches, new_param_vals, new_opt_states = step_fn(
            feed_vals, param_vals, opt_states, lrs, steps)

        if new_param_vals is not None:
            for p, nv in zip(params, new_param_vals):
                p._value = nv
            for (optimizer, _loss, opt_params), accs in zip(
                    prog._opts, new_opt_states):
                ps = self._opt_params(prog, optimizer, opt_params)
                for p, na in zip(ps, accs):
                    optimizer._accumulators[id(p)] = na
                optimizer._step_count += 1

        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    # ------------------------------------------------------------------
    def _run_startup(self, prog):
        params = prog._params or G.default_main_program()._params
        if prog._param_snapshot is None:
            prog._param_snapshot = {
                id(p): np.asarray(p._value) for p in params}
        else:
            for p in params:
                snap = prog._param_snapshot.get(id(p))
                if snap is not None:
                    p._value = jnp.asarray(snap)

    def _resolve_fetch(self, prog, f):
        if isinstance(f, str):
            return prog.var(f)
        if isinstance(f, (G.StaticVar, Parameter, Tensor)):
            return f
        raise TypeError(f"bad fetch_list entry: {f!r}")

    def _shard_opt_states(self, prog, opt_states):
        """Static-graph ZeRO-1 (~ meta_optimizers/sharding_optimizer.py:45):
        when an optimizer carries `_shard_states_axis` and the global mesh
        has that axis, its accumulators are placed with NamedShardings so
        each device holds 1/N of every moment tensor; XLA's sharding
        propagation keeps the compiled update's outputs on the same
        layout (the program-rewrite the reference does by inserting
        broadcast/reduce ops collapses into GSPMD)."""
        for (optimizer, _loss, opt_params), accs in zip(prog._opts,
                                                        opt_states):
            mesh, axis = optimizer._zero_mesh()
            if mesh is None:
                continue
            ps = self._opt_params(prog, optimizer, opt_params)
            for p, a in zip(ps, accs):
                pspec = getattr(p, "sharding_spec", None)
                for k, arr in list(a.items()):
                    if not hasattr(arr, "ndim") or arr.ndim < 1:
                        continue
                    sh = optimizer._state_sharding(arr, mesh, axis, pspec)
                    if arr.sharding != sh:
                        a[k] = jax.device_put(arr, sh)

    @staticmethod
    def _opt_params(prog, optimizer, opt_params):
        if opt_params:
            ps = opt_params
        elif optimizer._parameters:
            ps = optimizer._parameters
        else:
            ps = prog._params
        return [p for p in ps if p.trainable]

    # ------------------------------------------------------------------
    def _build(self, prog, feed_names, fetch_vars):
        """Compile one (program, feed signature, fetch set) entry."""
        params = list(prog._params)
        data_vars = [prog._datas[n] for n in feed_names]
        opt_entries = [(opt, loss, self._opt_params(prog, opt, ps))
                       for opt, loss, ps in prog._opts]
        train = bool(opt_entries)

        grad_fetches = [f for f in fetch_vars if isinstance(f, G.GradVar)]
        need_grads = train or bool(grad_fetches)
        # grads additionally wrt fed data vars named by fetched GradVars
        grad_data_wrts = [g.wrt for g in grad_fetches
                          if isinstance(g.wrt, G.StaticVar)]
        # loss used for pure append_backward/gradients fetches
        aux_losses = [g.loss for g in grad_fetches]
        assert len({id(loss) for _, loss, _ in opt_entries}
                   | {id(l) for l in aux_losses}) <= 1 or not need_grads, \
            "all grads in one program must flow from a single loss"
        loss_var = (opt_entries[0][1] if train
                    else (aux_losses[0] if aux_losses else None))

        def forward(env):
            # evaluate every fetch (memoized through the shared env)
            outs = []
            for f in fetch_vars:
                if isinstance(f, G.GradVar):
                    outs.append(None)  # filled after grad computation
                elif G._is_symbolic(f):
                    outs.append(_eval_var(f, env))
                else:  # concrete Tensor/Parameter fetch
                    outs.append(env.get(id(f), f._value))
            return outs

        def make_env(feed_vals, param_vals, data_grads_vals=None):
            env = {}
            for dv, v in zip(data_vars, feed_vals):
                env[id(dv)] = v
            for p, v in zip(params, param_vals):
                env[id(p)] = v
            return env

        def step(feed_vals, param_vals, opt_states, lrs, steps):
            if not need_grads:
                env = make_env(feed_vals, param_vals)
                return forward(env), None, None

            diff_feed_idx = [i for i, dv in enumerate(data_vars)
                             if any(g is dv for g in grad_data_wrts)]

            def loss_fn(pvals, dvals):
                fv = list(feed_vals)
                for i, v in zip(diff_feed_idx, dvals):
                    fv[i] = v
                env = make_env(fv, pvals)
                lv = _eval_var(loss_var, env) if loss_var is not None else 0.
                return lv, forward(env)

            (loss_val, outs), (pgrads, dgrads) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True)(
                param_vals, [feed_vals[i] for i in diff_feed_idx])

            grad_by_id = {id(p): g for p, g in zip(params, pgrads)}
            for i, g in zip(diff_feed_idx, dgrads):
                grad_by_id[id(data_vars[i])] = g
            for k, f in enumerate(fetch_vars):
                if isinstance(f, G.GradVar):
                    outs[k] = grad_by_id[id(f.wrt)]

            new_param_vals = list(param_vals)
            new_opt_states = []
            if train:
                pos = {id(p): i for i, p in enumerate(params)}
                for (optimizer, _loss, ps), accs, lr, stp in zip(
                        opt_entries, opt_states, lrs, steps):
                    grads = [grad_by_id[id(p)].astype(jnp.float32)
                             for p in ps]
                    grads = optimizer._apply_grad_clip(ps, grads)
                    new_accs = []
                    for p, g, a in zip(ps, grads, accs):
                        nv, na = optimizer._update_with_master(
                            new_param_vals[pos[id(p)]], g, a, lr, stp)
                        new_param_vals[pos[id(p)]] = nv
                        new_accs.append(na)
                    new_opt_states.append(new_accs)
            return outs, (new_param_vals if train else None), \
                (new_opt_states if train else None)

        return jax.jit(step)


def _dataset_batches(dataset):
    """Iterate batches from a fleet Dataset (InMemoryDataset/QueueDataset)
    or any iterable of feed tuples."""
    return iter(dataset)


def _install_dataset_loops():
    """Executor.train_from_dataset / infer_from_dataset.

    ~ framework/trainer.h MultiTrainer + Executor::RunFromDataset
    (framework/executor.cc:157): the reference spawns DeviceWorker threads
    pulling from a C++ DataFeed; here each batch feeds the jit-compiled
    program (XLA's async dispatch keeps the device busy while the host
    prepares the next feed — the HogwildWorker role)."""

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        return self._run_from_dataset(program, dataset, fetch_list,
                                      print_period, debug)

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        return self._run_from_dataset(program, dataset, fetch_list,
                                      print_period, debug)

    def _run_from_dataset(self, program, dataset, fetch_list, print_period,
                          debug):
        prog = program if program is not None else G.default_main_program()
        if isinstance(prog, CompiledProgram):
            prog = prog._program
        if dataset is None:
            raise ValueError("dataset must be provided")
        feed_names = sorted(prog._datas)
        last = None
        for it, batch in enumerate(_dataset_batches(dataset)):
            if not isinstance(batch, (tuple, list)):
                batch = (batch,)
            feed = dict(zip(feed_names, batch))
            last = self.run(prog, feed=feed, fetch_list=fetch_list)
            if debug and fetch_list and it % print_period == 0:
                print(f"[dataset iter {it}] "
                      + " ".join(str(v) for v in last))
        return last

    Executor.train_from_dataset = train_from_dataset
    Executor.infer_from_dataset = infer_from_dataset
    Executor._run_from_dataset = _run_from_dataset


_install_dataset_loops()
