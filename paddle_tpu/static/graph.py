"""Static-graph capture: Program / StaticVar / data / program_guard.

TPU-native equivalent of the reference's ProgramDesc stack
(paddle/fluid/framework/framework.proto:236 ProgramDesc -> BlockDesc:212 ->
OpDesc:50; python mirror python/paddle/fluid/framework.py Program/Block/
Variable). Instead of a protobuf op list interpreted by an executor, ops
applied to symbolic ``StaticVar`` inputs are captured as a functional DAG
(the jaxpr-before-the-jaxpr); ``Executor.run`` composes the DAG into one
function of (feeds, params) and ``jax.jit``-compiles it per feed signature —
the InterpreterCore/CINN roles collapse into XLA.

Dynamic dims: ``static.data`` accepts -1/None dims (framework.py Variable
semantics). Shape inference runs with a probe extent; dims that inherit the
probe report as -1. Compilation is per concrete feed signature, so the
executed program always has static shapes (XLA requirement).
"""
from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dtypes
from ..core.tensor import Parameter, Tensor

__all__ = [
    "Program", "StaticVar", "GradVar", "data", "program_guard",
    "default_main_program", "default_startup_program", "enable_static",
    "disable_static", "in_static_mode", "append_backward", "gradients",
]

# probe extent substituted for -1/None dims during shape inference; any
# inferred dim equal to it is reported back as -1 (dynamic)
_PROBE = 97


class OpNode:
    """One captured op (~ OpDesc framework.proto:50): the raw jax-traceable
    fn, its positional inputs (StaticVar | Tensor | python), static attrs,
    and the output vars."""

    __slots__ = ("name", "fn", "args", "kwargs", "out_vars", "single")

    def __init__(self, name, fn, args, kwargs):
        self.name = name
        self.fn = fn
        self.args = list(args)
        self.kwargs = dict(kwargs)
        self.out_vars: List["StaticVar"] = []
        self.single = True


class StaticVar(Tensor):
    """Symbolic variable (~ framework.py Variable:1212): shape/dtype known,
    no value until ``Executor.run``. Flows through the same python op APIs
    as eager Tensors; the dispatcher reroutes ops on it into graph capture.
    """

    _symbolic = True
    _counter = 0

    def __init__(self, shape, dtype, name=None, node=None, out_index=0,
                 is_data=False):
        # deliberately not calling Tensor.__init__: there is no value
        self._shape = tuple(
            -1 if (d is None or int(d) < 0) else int(d) for d in shape)
        self._probe_shape = tuple(
            _PROBE if d == -1 else d for d in self._shape)
        self._jdtype = jnp.dtype(_dtypes.convert_dtype(dtype))
        self.stop_gradient = True
        self._grad = None
        self._grad_node = None
        self._output_index = out_index
        self._node: Optional[OpNode] = node
        self.is_data = is_data
        self.persistable = False
        if name is None:
            name = f"_generated_var_{StaticVar._counter}"
            StaticVar._counter += 1
        self.name = name

    # ---- abstract properties (shadow Tensor's value-backed ones) ----------
    @property
    def _value(self):
        raise RuntimeError(
            f"StaticVar '{self.name}' has no value at graph-build time; "
            "values exist only inside Executor.run (feed it or fetch it)")

    @property
    def shape(self):
        return list(self._shape)

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dtype(self):
        return np.dtype(self._jdtype)

    @property
    def size(self):
        if -1 in self._shape:
            return -1
        return int(np.prod(self._shape)) if self._shape else 1

    def numpy(self):
        raise RuntimeError(
            f"StaticVar '{self.name}' is symbolic; fetch it via "
            "Executor.run(fetch_list=[var]) to get a value")

    def aval(self) -> jax.ShapeDtypeStruct:
        return jax.ShapeDtypeStruct(self._probe_shape, self._jdtype)

    def __repr__(self):
        return (f"StaticVar(name={self.name}, shape={list(self._shape)}, "
                f"dtype={self.dtype.name})")

    def __hash__(self):
        return id(self)


class GradVar(StaticVar):
    """Symbolic gradient d(loss)/d(wrt) (~ the grad vars append_backward
    creates, fluid/backward.py). ``wrt`` is a Parameter or a data StaticVar;
    the executor computes it with jax.grad over the composed program."""

    def __init__(self, loss: StaticVar, wrt):
        shape = wrt.shape if isinstance(wrt, StaticVar) \
            else list(wrt._value.shape)
        dt = wrt.dtype
        super().__init__(shape, dt, name=f"{getattr(wrt, 'name', 'w')}@GRAD")
        self.loss = loss
        self.wrt = wrt


class Program:
    """~ fluid.Program (framework.py): the captured graph + its parameters +
    appended optimizer steps. There is one flat block; control flow is
    lax.cond/scan inside ops rather than sub-blocks."""

    _counter = 0

    def __init__(self):
        self._datas: Dict[str, StaticVar] = {}
        self._vars: Dict[str, StaticVar] = {}
        self._params: List[Parameter] = []
        self._param_ids = set()
        self._opts: List[tuple] = []     # (optimizer, loss_var, params|None)
        self._layers: List[Any] = []     # static.nn layers kept alive
        self._n_ops = 0
        self._version = 0
        self._param_snapshot: Optional[Dict[int, np.ndarray]] = None
        self.random_seed = 0
        self.id = Program._counter
        Program._counter += 1

    # ---- registration ------------------------------------------------------
    def _add_param(self, p: Parameter):
        if id(p) not in self._param_ids:
            self._param_ids.add(id(p))
            self._params.append(p)
            self._version += 1

    def _add_var(self, v: StaticVar):
        self._vars[v.name] = v

    def _append_opt(self, optimizer, loss, parameters=None):
        self._opts.append((optimizer, loss, parameters))
        self._version += 1

    # ---- paddle API compat -------------------------------------------------
    def global_block(self):
        return self

    @property
    def blocks(self):
        return [self]

    def all_parameters(self):
        return list(self._params)

    def list_vars(self):
        return list(self._datas.values()) + list(self._vars.values())

    def var(self, name):
        if name in self._datas:
            return self._datas[name]
        if name in self._vars:
            return self._vars[name]
        for p in self._params:
            if p.name == name:
                return p
        raise KeyError(f"no var named {name!r} in program")

    has_var = lambda self, name: (name in self._datas or name in self._vars)

    def clone(self, for_test: bool = False):
        # vars are shared; cloning is a view (the reference deep-copies the
        # proto, but our graph is immutable-by-construction)
        c = Program.__new__(Program)
        c.__dict__ = dict(self.__dict__)
        c.id = Program._counter  # distinct executor compile-cache identity
        Program._counter += 1
        if for_test:
            c._opts = []
        return c

    def __repr__(self):
        return (f"Program(id={self.id}, datas={list(self._datas)}, "
                f"params={len(self._params)}, ops={self._n_ops}, "
                f"opt_steps={len(self._opts)})")


_default_main = Program()
_default_startup = Program()
_static_mode = False


def default_main_program() -> Program:
    return _default_main


def default_startup_program() -> Program:
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Program = None):
    """~ fluid.program_guard."""
    global _default_main, _default_startup
    old_m, old_s = _default_main, _default_startup
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    try:
        yield
    finally:
        _default_main, _default_startup = old_m, old_s


def enable_static():
    """~ paddle.enable_static (python/paddle/fluid/framework.py): flips the
    dispatcher into graph-capture mode for ops touching StaticVars."""
    global _static_mode
    _static_mode = True
    from ..ops import dispatch as _d
    _d.STATIC_MODE = True


def disable_static(place=None):
    global _static_mode
    _static_mode = False
    from ..ops import dispatch as _d
    _d.STATIC_MODE = False


def in_static_mode() -> bool:
    return _static_mode


def data(name: str, shape: Sequence[int], dtype=None, lod_level=0) -> StaticVar:
    """~ paddle.static.data (python/paddle/fluid/data.py): a feed slot."""
    if dtype is None:
        dtype = _dtypes.get_default_dtype()
    v = StaticVar(shape, dtype, name=name, is_data=True)
    default_main_program()._datas[name] = v
    return v


def _is_symbolic(x) -> bool:
    return getattr(x, "_symbolic", False)


def capture(name: str, fn, args, kwargs):
    """Append one op to the default main program (~ LayerHelper.append_op →
    block.append_op, framework.py Operator:2533). Computes output shapes
    through jax.eval_shape (the infermeta role) and returns StaticVars."""
    prog = default_main_program()

    abstract = []
    tensor_idx = []
    for i, a in enumerate(args):
        if _is_symbolic(a):
            abstract.append(a.aval())
            tensor_idx.append(i)
        elif isinstance(a, Tensor):
            if isinstance(a, Parameter):
                prog._add_param(a)
                # the paired startup program owns initialization state
                default_startup_program()._add_param(a)
            abstract.append(jax.ShapeDtypeStruct(
                tuple(a._value.shape), a._value.dtype))
            tensor_idx.append(i)

    def _infer(*xs):
        # non-tensor args (shape lists, axes, scalars) stay static — they
        # are op attributes, not data (~ OpDesc attrs vs inputs)
        merged = list(args)
        for j, x in zip(tensor_idx, xs):
            merged[j] = x
        return fn(*merged, **kwargs)

    out_aval = jax.eval_shape(_infer, *abstract)
    single = not isinstance(out_aval, (tuple, list))
    avals = (out_aval,) if single else tuple(out_aval)

    node = OpNode(name, fn, args, kwargs)
    node.single = single
    probing = any(_is_symbolic(a) and -1 in a._shape for a in args)
    outs = []
    for i, av in enumerate(avals):
        shp = [(-1 if (probing and d == _PROBE) else d) for d in av.shape]
        v = StaticVar(shp, av.dtype, node=node, out_index=i)
        # keep exact probe shape for downstream inference (PROBE**2 etc.
        # would otherwise be lost by the -1 round trip)
        v._probe_shape = tuple(av.shape)
        outs.append(v)
        prog._add_var(v)
    node.out_vars = outs
    prog._n_ops += 1
    prog._version += 1
    return outs[0] if single else tuple(outs)


def append_backward(loss: StaticVar, parameter_list=None, no_grad_set=None):
    """~ fluid.backward.append_backward: returns [(param, grad_var)]."""
    prog = default_main_program()
    params = parameter_list if parameter_list is not None else prog._params
    params = [p for p in params
              if isinstance(p, Parameter) and p.trainable]
    return [(p, GradVar(loss, p)) for p in params]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """~ paddle.static.gradients: symbolic grads of targets wrt inputs."""
    tgts = targets if isinstance(targets, (list, tuple)) else [targets]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    assert len(tgts) == 1, "gradients() supports a single scalar target"
    return [GradVar(tgts[0], x) for x in ins]
