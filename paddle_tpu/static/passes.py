"""Graph pass framework over the captured Program DAG.

~ paddle/fluid/framework/ir/ (Pass/PassRegistry pass.h:53,215, REGISTER_PASS
:317; ~150 passes). TPU reality: XLA performs fusion/DCE/CSE/layout inside
jit, so the pass layer here is thin and targets what XLA can't see —
program-level dead op elimination (fewer ops to trace), constant folding of
host-known subgraphs (smaller jaxprs), and analysis passes that report
structure (op stats). The registry/apply API mirrors the reference so
downstream tooling (distributed passes in distributed/passes-style) can hook
in.
"""
from __future__ import annotations

from typing import Callable, Dict, List

from .graph import OpNode, Program, StaticVar

PASS_REGISTRY: Dict[str, Callable] = {}


def register_pass(name: str):
    """~ REGISTER_PASS(name, class) ir/pass.h:317."""
    def deco(fn):
        PASS_REGISTRY[name] = fn
        fn.pass_name = name
        return fn
    return deco


def apply_pass(program: Program, name: str, **kwargs):
    """~ Pass::Apply(graph)."""
    if name not in PASS_REGISTRY:
        raise KeyError(f"no pass registered under {name!r}; "
                       f"have {sorted(PASS_REGISTRY)}")
    return PASS_REGISTRY[name](program, **kwargs)


def apply_build_strategy(program: Program, build_strategy=None,
                         fetch_vars=None):
    """Run the standard pipeline (~ BuildStrategy-driven pass list)."""
    stats = {}
    stats["dead_ops_removed"] = apply_pass(
        program, "dead_code_elimination", fetch_vars=fetch_vars)
    stats["ops_folded"] = apply_pass(program, "constant_folding")
    return stats


def _reachable_nodes(program: Program, fetch_vars) -> set:
    seen_nodes = set()
    stack = []
    for v in fetch_vars or []:
        node = getattr(v, "_node", None)
        if node is not None:
            stack.append(node)
    while stack:
        node = stack.pop()
        if id(node) in seen_nodes:
            continue
        seen_nodes.add(id(node))
        for a in node.args:
            sub = getattr(a, "_node", None)
            if sub is not None:
                stack.append(sub)
    return seen_nodes


@register_pass("dead_code_elimination")
def dead_code_elimination(program: Program, fetch_vars=None) -> int:
    """Drop captured vars whose producing ops can't reach any fetch target
    (~ ir passes' DCE; the reference prunes ProgramDesc similarly in
    framework/prune.cc). Returns number of removed vars."""
    if not fetch_vars:
        return 0
    live = _reachable_nodes(program, fetch_vars)
    dead = [name for name, v in program._vars.items()
            if getattr(v, "_node", None) is not None
            and id(v._node) not in live]
    for name in dead:
        del program._vars[name]
    program._version += 1
    return len(dead)


@register_pass("constant_folding")
def constant_folding(program: Program) -> int:
    """Evaluate ops with no feed slot upstream: outputs become constants
    (stamped as ``_const_value``, honored by the Executor before tracing),
    shrinking the jitted program (~ ir/constant_folding_pass). Parameters
    do NOT count as constants — they change across steps. Returns the
    number of folded ops."""
    from ..core.tensor import Parameter, Tensor

    folded = 0
    seen_nodes = set()
    # program._vars is insertion-ordered = topological
    for v in list(program._vars.values()):
        node = getattr(v, "_node", None)
        if node is None or id(node) in seen_nodes:
            continue
        seen_nodes.add(id(node))
        if getattr(node.out_vars[0], "_const_value", None) is not None:
            continue
        args_c = []
        ok = True
        for a in node.args:
            if isinstance(a, StaticVar):
                cv = getattr(a, "_const_value", None)
                if cv is None:
                    ok = False
                    break
                args_c.append(cv)
            elif isinstance(a, Parameter):
                ok = False
                break
            elif isinstance(a, Tensor):
                args_c.append(a._value)
            else:
                args_c.append(a)
        if not ok:
            continue
        try:
            out = node.fn(*args_c, **node.kwargs)
        except Exception:
            continue
        outs = (out,) if node.single else tuple(out)
        for ov, val in zip(node.out_vars, outs):
            ov._const_value = val
        folded += 1
    program._version += 1
    return folded


def freeze_feed(var, value):
    """Bind a feed slot to a fixed value so constant_folding can collapse
    everything downstream of it (~ inference freezing: feed vars replaced
    by persistable constants before the ir pass pipeline runs)."""
    import jax.numpy as jnp
    var._const_value = jnp.asarray(value)
    return var


@register_pass("op_stats")
def op_stats(program: Program) -> Dict[str, int]:
    """Analysis pass: op-name histogram (~ ir cost_model inputs)."""
    counts: Dict[str, int] = {}
    seen = set()
    for v in program._vars.values():
        node = getattr(v, "_node", None)
        if node is None or id(node) in seen:
            continue
        seen.add(id(node))
        counts[node.name] = counts.get(node.name, 0) + 1
    return counts
