"""Signal ops: stft/istft. ~ python/paddle/signal.py."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor
from .ops.dispatch import apply_op


def frame(x, frame_length, hop_length, axis=-1):
    def fn(v):
        n = v.shape[axis]
        num = 1 + (n - frame_length) // hop_length
        idx = (np.arange(frame_length)[None, :]
               + hop_length * np.arange(num)[:, None])
        return jnp.take(v, jnp.asarray(idx), axis=axis)
    return apply_op("frame", fn, x)


def overlap_add(x, hop_length, axis=-1):
    def fn(v):
        # v: (..., frames, frame_length) on last two axes
        frames, flen = v.shape[-2], v.shape[-1]
        out_len = (frames - 1) * hop_length + flen
        out = jnp.zeros(v.shape[:-2] + (out_len,), v.dtype)
        for i in range(frames):
            out = out.at[..., i * hop_length:i * hop_length + flen].add(
                v[..., i, :])
        return out
    return apply_op("overlap_add", fn, x)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    wv = window._value if isinstance(window, Tensor) else window

    def fn(v):
        val = v
        if center:
            pad = n_fft // 2
            val = jnp.pad(val, [(0, 0)] * (val.ndim - 1) + [(pad, pad)],
                          mode=pad_mode)
        n = val.shape[-1]
        num = 1 + (n - n_fft) // hop_length
        idx = (np.arange(n_fft)[None, :]
               + hop_length * np.arange(num)[:, None])
        frames = jnp.take(val, jnp.asarray(idx), axis=-1)  # (..., num, n_fft)
        if wv is not None:
            w = jnp.asarray(wv)
            if win_length < n_fft:
                lpad = (n_fft - win_length) // 2
                w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
            frames = frames * w
        if onesided:
            spec = jnp.fft.rfft(frames, axis=-1)
        else:
            spec = jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(n_fft)
        return jnp.swapaxes(spec, -1, -2)  # (..., freq, frames)
    return apply_op("stft", fn, x)


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    wv = window._value if isinstance(window, Tensor) else window

    def fn(spec):
        s = jnp.swapaxes(spec, -1, -2)  # (..., frames, freq)
        if normalized:
            s = s * jnp.sqrt(n_fft)
        if onesided:
            frames = jnp.fft.irfft(s, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(s, axis=-1).real
        if wv is not None:
            w = jnp.asarray(wv)
            if win_length < n_fft:
                lpad = (n_fft - win_length) // 2
                w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
            frames = frames * w
            wsq = w * w
        else:
            wsq = jnp.ones((n_fft,))
        nf = frames.shape[-2]
        out_len = (nf - 1) * hop_length + n_fft
        out = jnp.zeros(frames.shape[:-2] + (out_len,), frames.dtype)
        norm = jnp.zeros((out_len,))
        for i in range(nf):
            sl = slice(i * hop_length, i * hop_length + n_fft)
            out = out.at[..., sl].add(frames[..., i, :])
            norm = norm.at[sl].add(wsq)
        out = out / jnp.maximum(norm, 1e-11)
        if center:
            pad = n_fft // 2
            out = out[..., pad:out.shape[-1] - pad]
        if length is not None:
            out = out[..., :length]
        return out
    return apply_op("istft", fn, x)
