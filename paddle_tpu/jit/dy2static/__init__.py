"""dy2static: AST-level dynamic-to-static conversion.

~ python/paddle/fluid/dygraph/dygraph_to_static/ (20 AST transformer files:
ifelse_transformer.py, loop_transformer.py, logical_transformer.py,
convert_operators.py, convert_call_func.py, program_translator.py).

The reference rewrites Python control flow into ProgramDesc cond/while ops.
TPU-native: the same AST rewrite, but the runtime converters dispatch to
``lax.cond`` / ``lax.while_loop`` when the predicate is a traced tensor and
to plain Python control flow otherwise — so one source supports eager runs
AND jit tracing with data-dependent branches.

Pipeline (``convert_to_static``):
  source -> ast.parse -> LogicalTransformer (and/or/not -> converter calls)
         -> ForToWhileTransformer (for-range -> while)
         -> WhileTransformer (while -> functional cond_fn/body_fn + carry)
         -> IfElseTransformer (if -> functional branches + carry)
         -> compile + exec in the original closure environment.
"""
from .convert_operators import (  # noqa: F401
    convert_ifelse, convert_logical_and, convert_logical_not,
    convert_logical_or, convert_while_loop, UndefinedVar,
)
from .transformer import convert_to_static, code_of  # noqa: F401
