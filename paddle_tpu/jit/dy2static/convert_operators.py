"""Runtime converters the transformed AST calls into.

~ dygraph_to_static/convert_operators.py (convert_ifelse, convert_while_loop,
convert_logical_and/or/not): each checks whether the control value is a
tensor/tracer; tensor -> compiled control flow (lax.cond / lax.while_loop),
plain Python value -> native control flow. This runtime dispatch is what
lets one transformed source serve both eager and traced execution.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor


class UndefinedVar:
    """Sentinel for names that may be defined only inside a branch
    (~ dygraph_to_static/utils.py UndefinedVar)."""

    def __init__(self, name: str):
        self.name = name

    def __repr__(self):
        return f"UndefinedVar({self.name!r})"


def _is_traced(x) -> bool:
    if isinstance(x, Tensor):
        return isinstance(x._value, jax.core.Tracer)
    return isinstance(x, jax.core.Tracer)


def _is_tensorish(x) -> bool:
    return isinstance(x, (Tensor, jax.Array)) or isinstance(
        x, jax.core.Tracer)


def _to_bool_value(x):
    if isinstance(x, Tensor):
        return x._value
    return x


def _unwrap_tree(tree):
    return jax.tree.map(
        lambda x: x._value if isinstance(x, Tensor) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def _check_carry(name, tree):
    leaves = jax.tree.leaves(
        tree, is_leaf=lambda x: isinstance(x, UndefinedVar))
    for leaf in leaves:
        if isinstance(leaf, UndefinedVar):
            raise ValueError(
                f"variable '{leaf.name}' is set in only one branch of a "
                f"tensor-dependent `{name}` — both paths must define it "
                "for compiled control flow (the reference raises the same "
                "constraint from its IfElse transformer)")


def _wrap_tree(tree):
    return jax.tree.map(
        lambda x: Tensor(x) if isinstance(x, (jax.Array, jax.core.Tracer))
        else x, tree)


def _partition(carry):
    """Split the carry tuple into the compiled subset (tensor/array/scalar
    leaves defined before the block) and static passthrough values
    (UndefinedVar temps, strings, arbitrary objects).

    The compiled subset is what rides through lax.cond/while_loop; statics
    are re-inserted on the way out (matching the reference's treatment of
    non-Variable loop vars)."""
    flat = list(carry)
    dyn_idx = []
    for i, v in enumerate(flat):
        if isinstance(v, UndefinedVar):
            continue
        if _is_tensorish(v) or isinstance(v, (int, float, bool, complex)):
            dyn_idx.append(i)
    return flat, dyn_idx


def _to_full(flat, dyn_idx, sub):
    out = list(flat)
    for j, i in enumerate(dyn_idx):
        out[i] = sub[j]
    return tuple(out)


def convert_ifelse(pred, true_fn, false_fn, carry):
    """``out = convert_ifelse(cond, true_fn, false_fn, (a, b))``.

    Tensor/tracer pred -> lax.cond with both branches traced over the carry;
    Python pred -> call the taken branch only.
    """
    if _is_tensorish(pred):
        pv = _to_bool_value(pred)
        if getattr(pv, "ndim", 0) > 0:
            pv = jnp.all(pv)
        if not _is_traced(pred):
            # concrete device value in eager mode: take one branch natively
            return true_fn(carry) if bool(pv) else false_fn(carry)
        flat, dyn_idx = _partition(carry)

        def run(branch_fn, sub):
            out = branch_fn(_wrap_tree(_to_full(flat, dyn_idx, sub)))
            out = tuple(out) if isinstance(out, (tuple, list)) else (out,)
            for v in out:
                if isinstance(v, UndefinedVar):
                    raise ValueError(
                        f"variable '{v.name}' must be defined in both "
                        "branches of a tensor-dependent `if` (or before "
                        "it) for compiled control flow")
            return tuple(_unwrap_tree(list(out)))

        def t(sub):
            return run(true_fn, sub)

        def f(sub):
            return run(false_fn, sub)
        sub0 = tuple(_unwrap_tree([flat[i] for i in dyn_idx]))
        out = jax.lax.cond(pv, t, f, sub0)
        return _wrap_tree(tuple(out))
    return true_fn(carry) if pred else false_fn(carry)


def convert_while_loop(cond_fn, body_fn, carry):
    """Tensor-valued condition -> lax.while_loop; else native while."""
    probe = cond_fn(carry)
    if _is_traced(probe):
        flat, dyn_idx = _partition(carry)

        def cond(sub):
            r = cond_fn(_wrap_tree(_to_full(flat, dyn_idx, sub)))
            r = r._value if isinstance(r, Tensor) else r
            return jnp.all(r) if getattr(r, "ndim", 0) > 0 else r

        def body(sub):
            out = body_fn(_wrap_tree(_to_full(flat, dyn_idx, sub)))
            out = tuple(out) if isinstance(out, (tuple, list)) else (out,)
            return tuple(_unwrap_tree([out[i] for i in dyn_idx]))
        sub0 = tuple(_unwrap_tree([flat[i] for i in dyn_idx]))
        res = jax.lax.while_loop(cond, body, sub0)
        return _wrap_tree(_to_full(flat, dyn_idx, tuple(res)))
    while _bool(probe):
        carry = body_fn(carry)
        probe = cond_fn(carry)
    return carry


def _bool(x):
    if isinstance(x, Tensor):
        return bool(jnp.all(x._value))
    return bool(x)


def convert_logical_and(lhs_fn, rhs_fn):
    """``a and b`` with tensor short-circuit semantics preserved for
    Python values (rhs not evaluated when lhs falsy and plain)."""
    lhs = lhs_fn()
    if _is_tensorish(lhs):
        rhs = rhs_fn()
        if _is_tensorish(rhs):
            return Tensor(jnp.logical_and(_to_bool_value(lhs),
                                          _to_bool_value(rhs)))
        return Tensor(jnp.logical_and(_to_bool_value(lhs), bool(rhs)))
    if not lhs:
        return lhs
    return rhs_fn()


def convert_logical_or(lhs_fn, rhs_fn):
    lhs = lhs_fn()
    if _is_tensorish(lhs):
        rhs = rhs_fn()
        if _is_tensorish(rhs):
            return Tensor(jnp.logical_or(_to_bool_value(lhs),
                                         _to_bool_value(rhs)))
        return Tensor(jnp.logical_or(_to_bool_value(lhs), bool(rhs)))
    if lhs:
        return lhs
    return rhs_fn()


def convert_logical_not(x):
    if _is_tensorish(x):
        return Tensor(jnp.logical_not(_to_bool_value(x)))
    return not x


def convert_print(*args):
    """~ print_transformer.py convert_print: traced tensors print at
    runtime via the compiled program's host callback (jax.debug.print —
    the TPU-native Print op); plain values print natively."""
    if any(_is_traced(a) for a in args):
        fmt = " ".join("{}" for _ in args)
        jax.debug.print(fmt, *[_to_bool_value(a) if isinstance(a, Tensor)
                               else a for a in args])
        return
    print(*args)


def convert_assert(test, msg=None):
    """~ assert_transformer.py convert_assert (Assert op). Traced test ->
    host callback raising AssertionError when the compiled value is
    falsy; concrete -> native assert semantics."""
    if _is_traced(test):
        pv = _to_bool_value(test)
        if getattr(pv, "ndim", 0) > 0:
            pv = jnp.all(pv)

        def _check(ok):
            if not bool(ok):
                raise AssertionError(
                    msg if msg is not None else "dy2static assert failed")
        jax.debug.callback(_check, pv)
        return
    v = _to_bool_value(test)
    ok = bool(jnp.all(v)) if getattr(v, "ndim", 0) > 0 else bool(v)
    if not ok:
        raise AssertionError(msg if msg is not None else None)


def convert_var_dtype(x, dtype_name: str):
    """~ cast_transformer.py: bool/int/float(x) on a tensor becomes a
    dtype cast that survives tracing; concrete scalars keep native Python
    cast semantics so eager behavior is unchanged."""
    if _is_tensorish(x):
        v = _to_bool_value(x)
        if not _is_traced(x) and getattr(v, "ndim", 0) == 0:
            return {"bool": bool, "int": int,
                    "float": float}[dtype_name](v)
        # reference cast_transformer maps int -> int64; without x64 jax
        # would truncate (with a warning), so pick the widest available
        int_t = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
        target = {"bool": jnp.bool_, "int": int_t,
                  "float": jnp.float32}[dtype_name]
        return Tensor(v.astype(target))
    return {"bool": bool, "int": int, "float": float}[dtype_name](x)


def convert_len(x):
    """~ convert_operators.py convert_len: leading-dim length for tensors
    (static under tracing), native len() for containers."""
    if isinstance(x, Tensor):
        return x.shape[0]
    if isinstance(x, (jax.Array, jax.core.Tracer)):
        return x.shape[0]
    return len(x)
