"""paddle_tpu.jit: to_static trace-and-compile.

~ python/paddle/jit (dygraph_to_static ProgramTranslator:847,
StaticFunction:237, PartialProgramLayer). TPU-native design: instead of AST
rewriting into ProgramDesc, `to_static` traces the eager function with
jax.jit — the jaxpr is the Program, XLA is the executor, and the cache key
is the input signature (shape/dtype/tree) exactly like the reference's
program cache. Dynamic Python control flow must be expressed with
lax.cond/scan (the role the dy2static AST transformers play is subsumed by
jax's tracing contract).

jit.save/load serialize the traced StableHLO plus state_dict — the
deployment-export slot (save_inference_model analog).
"""
from __future__ import annotations

import functools
import os
import pickle
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd import tape as _tape
from ..core.tensor import Parameter, Tensor
from ..nn.layer.layers import Layer
from ..obs import metrics as _obs_metrics
from ..obs import trace as _obs_trace


def _unwrap_tree(tree):
    return jax.tree.map(
        lambda x: x._value if isinstance(x, Tensor) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def _wrap_tree(tree):
    return jax.tree.map(
        lambda x: Tensor(x) if isinstance(x, jax.Array) else x, tree)


class InputSpec:
    """~ paddle.static.InputSpec (python/paddle/static/input.py)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


class StaticFunction:
    """~ dygraph_to_static/program_translator.py StaticFunction:237.

    Wraps an eager function/Layer method; on call, runs it under jax.jit
    with Tensors bridged to tracers. Grad flows via the functional
    ``grad_fn`` (value_and_grad over the param tree) rather than the tape.
    """

    def __init__(self, fn: Callable, input_spec=None, layer: Layer | None = None):
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        self._jitted = None
        # the program-cache ledger behind cache_stats(); _cache_info is
        # the legacy (pre-obs) alias and stays the SAME dict
        self._cache_info = {"hits": 0, "misses": 0, "compiles": 0,
                            "last_compile_s": None}
        self._seen_sigs: set = set()
        # counters prefetched once — the per-call path must not pay
        # registry lookups (same discipline as the serving engine)
        self._ctr_hit = _obs_metrics.counter(
            "jit_cache_hits_total", "to_static program-cache hits")
        self._ctr_miss = _obs_metrics.counter(
            "jit_cache_misses_total", "to_static program-cache misses")
        self._hist_compile = _obs_metrics.histogram(
            "jit_compile_seconds", "wall seconds per to_static compile")

    @staticmethod
    def _signature(tree, training: bool):
        """The program-cache key: pytree structure + per-leaf
        shape/dtype (+ the static training flag) — the same signature
        jax.jit specializes on, so hit/miss counts what XLA caches.
        The treedef is hashable as-is; leaves reduce to (shape, dtype)
        tuples — no stringification on the call path."""
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        descr = tuple(
            (tuple(leaf.shape), leaf.dtype)
            if hasattr(leaf, "shape") and hasattr(leaf, "dtype")
            else ("scalar", type(leaf).__name__)
            for leaf in leaves)
        return (treedef, descr, bool(training))

    def cache_stats(self) -> dict:
        """Public program-cache statistics: ``hits`` / ``misses`` /
        ``compiles`` (misses whose dispatch actually grew the
        underlying jax.jit program cache — a re-trace that hit an
        already-compiled program is a miss but not a compile) and
        ``last_compile_s`` (wall seconds of the most recent compiling
        call). Mirrored into the obs metrics registry
        (``jit_cache_{hits,misses}_total``, ``jit_compile_seconds``)."""
        return dict(self._cache_info)

    def _build(self):
        layer = self._layer
        fn = self._fn
        # dy2static AST pass: Python control flow on tensors ->
        # lax.cond/while_loop converter calls (jit/dy2static/)
        try:
            if ProgramTranslator().enable_to_static:
                import inspect as _inspect
                import types as _types
                from .dy2static import convert_to_static
                if _inspect.ismethod(fn):
                    fn = _types.MethodType(
                        convert_to_static(fn.__func__), fn.__self__)
                else:
                    fn = convert_to_static(fn)
                self._converted_fn = fn
        except SyntaxError:
            pass

        def traced(params, args, kwargs, training):
            if layer is not None:
                old = layer.tree_flatten_params()
                layer.load_tree(params)
                was_training = layer.training
                layer.training = training
                try:
                    with _tape.no_grad():
                        out = fn(*_wrap_tree(args), **_wrap_tree(kwargs))
                finally:
                    layer.load_tree(old)
                    layer.training = was_training
            else:
                with _tape.no_grad():
                    out = fn(*_wrap_tree(args), **_wrap_tree(kwargs))
            return _unwrap_tree(out)

        self._jitted = jax.jit(traced, static_argnums=(3,))

    def __call__(self, *args, **kwargs):
        if self._jitted is None:
            self._build()
        params = self._layer.tree_flatten_params() if self._layer else {}
        args_u = _unwrap_tree(args)
        kwargs_u = _unwrap_tree(kwargs)
        training = self._layer.training if self._layer else False
        key = self._signature((params, args_u, kwargs_u), training)
        ci = self._cache_info
        if key in self._seen_sigs:
            ci["hits"] += 1
            self._ctr_hit.inc()
            out = self._jitted(params, args_u, kwargs_u, training)
        else:
            self._seen_sigs.add(key)
            ci["misses"] += 1
            self._ctr_miss.inc()
            try:
                c0 = int(self._jitted._cache_size())
            except Exception:
                c0 = None
            t0 = time.perf_counter()
            out = self._jitted(params, args_u, kwargs_u, training)
            # dispatch of a fresh signature blocks until trace+compile
            # finish (execution stays async), so this wall delta IS the
            # compile cost
            dt = time.perf_counter() - t0
            try:
                compiled = c0 is None or int(
                    self._jitted._cache_size()) > c0
            except Exception:
                compiled = True
            if compiled:
                ci["compiles"] += 1
                ci["last_compile_s"] = dt
                self._hist_compile.observe(dt)
                tr = _obs_trace.active()
                if tr is not None:
                    name = getattr(self._fn, "__qualname__",
                                   getattr(self._fn, "__name__", "fn"))
                    tr.instant("jit.compile", track="jit",
                               fn=str(name), wall_s=round(dt, 6))
        return _wrap_tree(out)

    @property
    def concrete_program(self):
        return self._jitted

    def get_traced(self, *example_args, **example_kwargs):
        """Return (jaxpr, lowered StableHLO text) for inspection/golden tests."""
        if self._jitted is None:
            self._build()
        params = self._layer.tree_flatten_params() if self._layer else {}
        lowered = self._jitted.lower(
            params, _unwrap_tree(example_args), _unwrap_tree(example_kwargs),
            self._layer.training if self._layer else False)
        return lowered


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """~ paddle.jit.to_static decorator."""
    def decorate(fn):
        if isinstance(fn, Layer):
            sf = StaticFunction(fn.forward, input_spec, layer=fn)
            fn._static_forward = sf
            orig_cls_call = fn.__class__.__call__

            def patched_call(*a, **kw):
                return sf(*a, **kw)
            fn.forward_static = sf
            return fn
        layer = getattr(fn, "__self__", None)
        sf = StaticFunction(fn, input_spec,
                            layer=layer if isinstance(layer, Layer) else None)
        functools.update_wrapper(sf, fn)
        return sf
    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    return None


def symbolic_export(frozen_fn, shapes_dtypes, warn_prefix="jit.save"):
    """Export ``frozen_fn`` with jax.export, mapping None/-1 dims to
    symbolic dimensions shared per dim-position (one artifact serves any
    batch/seq size); falls back to concrete example shapes (dynamic dims
    → 1) when the lowering is shape-dependent.

    ``shapes_dtypes``: list of (shape, np.dtype) with None/-1 for dynamic
    dims. Shared contract for jit.save and static.save_inference_model.
    """
    from jax import export as jax_export
    sym_args, any_sym, scope = [], False, None
    for shape, dtype in shapes_dtypes:
        if any(d is None or (isinstance(d, int) and d <= 0) for d in shape):
            if scope is None:
                scope = jax_export.SymbolicScope()
            # one symbol PER DIM POSITION shared across inputs: the
            # common case is a shared batch (and seq) dimension, and
            # distinct per-input symbols would make x + y between two
            # (None, 4) inputs un-exportable
            dims = ",".join(
                f"_d{j}" if (d is None or d <= 0) else str(d)
                for j, d in enumerate(shape))
            shp = jax_export.symbolic_shape(dims, scope=scope)
            any_sym = True
        else:
            shp = tuple(shape)
        sym_args.append(jax.ShapeDtypeStruct(shp, dtype))
    if any_sym:
        try:
            return jax_export.export(jax.jit(frozen_fn))(*sym_args)
        except Exception as e:  # shape-dependent lowering
            import warnings
            warnings.warn(
                f"{warn_prefix}: symbolic-shape export failed "
                f"({type(e).__name__}: {str(e)[:120]}); falling back "
                "to the concrete example shapes — the artifact will "
                "only accept those exact shapes", stacklevel=2)
    example = [jnp.zeros(tuple(1 if (d is None or d <= 0) else d
                               for d in shape), dtype)
               for shape, dtype in shapes_dtypes]
    return jax_export.export(jax.jit(frozen_fn))(*example)


def save(layer, path, input_spec=None, **configs):
    """~ paddle.jit.save: serialize compiled artifact + weights.

    Writes <path>.pdmodel (StableHLO text of the traced forward),
    <path>.pdiparams (pickled numpy state_dict) — same two-artifact contract
    as the reference's inference export (fluid/io.py save_inference_model).
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    state = {k: np.asarray(v._value)
             for k, v in layer.state_dict().items()} \
        if isinstance(layer, Layer) else {}
    hlo_text = None
    exported_bytes = None
    if input_spec:
        specs = [s if isinstance(s, InputSpec) else InputSpec(s)
                 for s in input_spec]
        example = [jnp.zeros(tuple(d if d and d > 0 else 1 for d in s.shape),
                             dtype=s.dtype) for s in specs]
        fn = layer.forward if isinstance(layer, Layer) else layer
        sf = StaticFunction(fn, layer=layer if isinstance(layer, Layer) else None)
        lowered = sf.get_traced(*[Tensor(e) for e in example])
        hlo_text = lowered.as_text()
        with open(path + ".pdmodel", "w") as f:
            f.write(hlo_text)
        # executable artifact: params closed over, tokens-only signature
        # (~ the reference's save_inference_model frozen program)
        params = layer.tree_flatten_params() if isinstance(layer, Layer) \
            else {}

        def frozen(*xs):
            if isinstance(layer, Layer):
                old = layer.tree_flatten_params()
                layer.load_tree(params)
                try:
                    with _tape.no_grad():
                        out = fn(*[Tensor(x) for x in xs])
                finally:
                    layer.load_tree(old)
            else:
                with _tape.no_grad():
                    out = fn(*[Tensor(x) for x in xs])
            return _unwrap_tree(out)

        # Shape polymorphism: InputSpec dims of None/-1 export as symbolic
        # dimensions (jax.export), so ONE artifact serves any batch size —
        # the dynamic-batching serving path (inference.DynamicBatcher)
        # depends on this.
        exp = symbolic_export(
            frozen, [(s.shape, np.dtype(s.dtype)) for s in specs])
        exported_bytes = exp.serialize()
        with open(path + ".pdexport", "wb") as f:
            f.write(exported_bytes)
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(state, f, protocol=4)
    meta = {"class": type(layer).__name__,
            "has_model": hlo_text is not None,
            "has_export": exported_bytes is not None}
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump(meta, f)


class TranslatedLayer(Layer):
    """~ paddle.jit.TranslatedLayer — runtime for loaded artifacts.

    When a ``.pdexport`` artifact exists (jax.export serialized module with
    weights frozen in), forward() executes it directly — the deployment
    path (NaiveExecutor/AnalysisPredictor slot)."""

    def __init__(self, state, hlo_text=None, exported=None):
        super().__init__()
        self._state = {k: Tensor(v) for k, v in state.items()}
        self._hlo_text = hlo_text
        self._exported = exported

    def state_dict(self, *a, **kw):
        return dict(self._state)

    def forward(self, *args):
        if self._exported is None:
            raise RuntimeError(
                "no executable artifact was saved (pass input_spec to "
                "jit.save); weights are available via state_dict()")
        vals = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
                for a in args]
        out = self._exported.call(*vals)
        return _wrap_tree(out)


def load(path, **configs):
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    hlo = None
    if os.path.exists(path + ".pdmodel"):
        with open(path + ".pdmodel") as f:
            hlo = f.read()
    exported = None
    if os.path.exists(path + ".pdexport"):
        from jax import export as jax_export
        with open(path + ".pdexport", "rb") as f:
            exported = jax_export.deserialize(bytearray(f.read()))
    return TranslatedLayer(state, hlo, exported)


class ProgramTranslator:
    """~ dygraph_to_static/program_translator.py ProgramTranslator:847 —
    process-wide switch for to_static tracing (singleton)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance.enable_to_static = True
        return cls._instance

    @classmethod
    def get_instance(cls):
        return cls()

    def enable(self, enable_to_static: bool):
        self.enable_to_static = bool(enable_to_static)


def enable_to_static(flag: bool = True):
    ProgramTranslator().enable(flag)


_verbosity = 0
_code_level = 0


def set_verbosity(level: int = 0, also_to_stdout: bool = False):
    """~ paddle.jit.set_verbosity — dy2static transform logging level."""
    global _verbosity
    _verbosity = int(level)


def set_code_level(level: int = 100, also_to_stdout: bool = False):
    """~ paddle.jit.set_code_level — which transformed code stage to print."""
    global _code_level
    _code_level = int(level)


class TracedLayer:
    """~ paddle.jit.TracedLayer (fluid/dygraph/jit.py): trace a dygraph
    layer into an executable program with example inputs."""

    def __init__(self, static_fn, layer, example_args):
        self._sf = static_fn
        self._layer = layer
        self._example = example_args

    @staticmethod
    def trace(layer, inputs):
        inputs = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
        sf = StaticFunction(layer.forward, layer=layer)
        out = sf(*inputs)
        return out, TracedLayer(sf, layer, inputs)

    def __call__(self, *args):
        return self._sf(*args)

    def save_inference_model(self, path, feed=None, fetch=None):
        specs = [InputSpec(t.shape, str(t.dtype)) for t in self._example]
        save(self._layer, path, input_spec=specs)

    @property
    def program(self):
        return self._sf.get_traced(*self._example)
