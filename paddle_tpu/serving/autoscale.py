"""SLO-driven elastic autoscaling: the control plane that closes the
detect -> act loop.

Every prior serving layer is an INPUT here. PR 9 detects (incidents,
burn rates, heartbeats), PR 6 can drain/join replicas, PR 8 assigns
prefill/decode roles, PR 3 can degrade admission budgets — but until
now no component ever ACTED on a signal: an incident was a report.
``Autoscaler`` is the policy that converts the shared ``IncidentLog``
stream plus live utilization probes into membership and policy
actions, so the fleet sizes itself to the workload instead of to the
peak:

- **scale up** (join): while a sustained error-budget burn is open
  (a ``BurnRateRule`` incident — the multi-window rule IS the
  "sustained" filter), a cold replica from the standby pool joins the
  shared virtual timeline. The triggering incidents close with
  resolution ``action_taken`` (``Incident.act``), stamping WHICH
  action resolved them into the postmortem evidence. The standby
  need NOT be a twin of the fleet it joins: since handoff placement
  scores tp-degree / page-geometry / codec mismatches by priced
  reshard cost instead of filtering them out, a
  compatible-but-unequal standby (say a tp=1 int8 decode box behind
  tp=2 fp prefill workers) is a legal join target — its imports pay
  the ``kv_reshard``/``kv_repage``/``kv_transcode`` spans on its own
  clock, which is the autoscaler's capacity-vs-transform-price
  trade, not a refusal.
- **scale down** (drain): when the budget has recovered (no open
  scale/degrade incidents) and cluster decode-slot utilization stays
  below ``drain_below`` for ``drain_sustain`` units, the idlest
  replica drains; its base name returns to the standby pool and a
  later join recycles it under a generation suffix (``s0#2``) — the
  router's exactly-once census is per-request, so recycled names
  conserve it.
- **role rebalance**: in a disaggregated cluster, when the measured
  prefill-chunk backlog per prefill worker crosses ``prefill_hi``
  (prefill-starved) or falls under ``prefill_lo`` while decode slots
  are exhausted (decode-starved), one dedicated worker flips
  prefill <-> decode. Role-less clusters never rebalance.
- **QoS degradation**: every page-severity incident is fanned into
  each live replica's ``QoSScheduler.note_incident`` THE MOMENT it
  opens (not at the next tick) — the scheduler's
  ``incident_degrade`` tier then clamps admission budgets while the
  incident stays open, shedding less by answering shorter. This is
  the "flip tiers before shedding" action the PR-3/PR-9 seam was
  declared for.

**Why it cannot oscillate.** Every action kind carries its own
cooldown, and join/drain are coupled by hysteresis: a drain is
refused within ``hold_after_join`` of any join (and vice versa within
``hold_after_drain``), a drain additionally requires the low-util
signal SUSTAINED for ``drain_sustain``, and a join requires an open
burn incident — which a drain-worthy (idle, budget-recovered) cluster
cannot have. ``count_oscillations`` is the audit the bench gate runs
over the action log.

**Determinism.** Decisions are evaluated ONLY at fixed ticks on the
shared virtual timeline (every ``interval`` units, scheduled like the
heartbeat probe ticks) plus the incident-open callback; all inputs
(incident state, per-replica load/backlog/slot probes) are themselves
deterministic under a seeded trace, so two replays produce a
byte-identical action log — the property the ``serving_autoscale``
gate asserts. With ``ClusterRouter(autoscale=None)`` none of this
code runs and the replay is byte-identical to a pre-autoscale router.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from ..obs.slo import SEVERITIES


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """The control plane's knobs. Times are virtual clock units.

    ``standby``: base names of the cold replica pool, join order.
    ``min_replicas`` / ``max_replicas``: live-fleet bounds the
    autoscaler may never cross (None = unbounded above).
    ``interval``: evaluation tick period on the shared timeline.
    ``join_cooldown`` / ``drain_cooldown`` / ``role_cooldown`` /
    ``degrade_cooldown``: minimum gap between two actions of the same
    kind (the degrade cooldown bounds LOGGING, not actuation — every
    page incident reaches the schedulers).
    ``hold_after_join`` / ``hold_after_drain``: the join<->drain
    hysteresis band — no drain within ``hold_after_join`` of a join,
    no join within ``hold_after_drain`` of a drain.
    ``drain_below`` / ``drain_sustain``: cluster busy-slot fraction
    that must hold for that long (with zero shedding and scale-up
    disarmed) before a drain fires.
    ``join_above``: while scale-up is ARMED, utilization at or above
    this also carries joins (the saturation path for fleets without
    an admission-shedding front door). The (``drain_below``,
    ``join_above``) dead band is directional hysteresis.
    ``recover_sustain``: how long the fleet must stay CALM (no sheds,
    no open scale incident) before the armed episode ends — a burn
    rule fires ONE incident per episode however many replicas short
    the fleet is, so the episode, not the incident, is what joins
    track.
    ``scale_on`` / ``scale_severity``: incident kinds (and minimum
    severity) that justify a join; the default is exactly the
    sustained multi-window ``BurnRateRule``.
    ``degrade``: fan page-severity incidents into every live
    replica's ``QoSScheduler.note_incident``.
    ``role_rebalance`` + ``prefill_hi`` / ``prefill_lo``: the
    disaggregated role-flip thresholds in prefill chunks per
    dedicated prefill worker (see module docstring).
    """

    standby: Tuple[str, ...] = ("s0", "s1")
    min_replicas: int = 1
    max_replicas: Optional[int] = None
    interval: float = 20.0
    join_cooldown: float = 60.0
    drain_cooldown: float = 240.0
    role_cooldown: float = 240.0
    degrade_cooldown: float = 60.0
    hold_after_join: float = 300.0
    hold_after_drain: float = 60.0
    drain_below: float = 0.35
    drain_sustain: float = 240.0
    join_above: float = 0.85
    recover_sustain: float = 120.0
    scale_on: Tuple[str, ...] = ("burn_rate",)
    scale_severity: str = "warn"
    degrade: bool = True
    role_rebalance: bool = False
    prefill_hi: float = 24.0
    prefill_lo: float = 2.0

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas is not None \
                and self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.interval <= 0:
            raise ValueError("interval must be > 0 clock units")
        for k in ("join_cooldown", "drain_cooldown", "role_cooldown",
                  "degrade_cooldown", "hold_after_join",
                  "hold_after_drain", "drain_sustain",
                  "recover_sustain"):
            if getattr(self, k) < 0:
                raise ValueError(f"{k} must be >= 0")
        if not 0.0 < self.drain_below <= 1.0:
            raise ValueError("drain_below is a busy fraction in (0, 1]")
        if not self.drain_below < self.join_above <= 1.0:
            raise ValueError("join_above is a busy fraction in "
                             "(drain_below, 1] — the dead band between "
                             "them is what keeps the loop from "
                             "oscillating")
        if self.scale_severity not in SEVERITIES:
            raise ValueError(f"scale_severity {self.scale_severity!r}: "
                             f"use one of {SEVERITIES}")
        if self.prefill_lo > self.prefill_hi:
            raise ValueError("prefill_lo must be <= prefill_hi")


class Autoscaler:
    """One run's autoscaling policy + its action log.

    The ``ClusterRouter`` owns execution; this object owns DECISION
    state (open incidents, cooldown stamps, the low-utilization
    timer, the standby pool) and the append-only ``actions`` log the
    determinism gate replays. Like a router, an Autoscaler runs ONCE
    — build a fresh one per replay, or the second run's log would
    start with the first run's cooldowns.
    """

    def __init__(self, config: Optional[AutoscaleConfig] = None, **kw):
        if config is not None and kw:
            raise ValueError("pass an AutoscaleConfig OR field "
                             "overrides, not both")
        self.cfg = config if config is not None else AutoscaleConfig(**kw)
        self.actions: List[dict] = []
        self._standby: List[str] = list(self.cfg.standby)
        self._open_scale: List[object] = []   # incidents justifying a join
        self._open_page: List[object] = []    # open page incidents (degrade)
        self._last = {"join": None, "drain": None, "role": None,
                      "degrade": None}
        self._low_since: Optional[float] = None
        # scale-up ARMED: a scale incident opened an episode that has
        # not yet RECOVERED (no-shed calm sustained). Joins continue
        # at cooldown cadence while armed and loss persists, because
        # one burn episode fires ONE incident per monitor however
        # many replicas short the fleet is.
        self._armed = False
        self._calm_since: Optional[float] = None
        self._last_sheds = 0
        self._attached = False

    # --- router plumbing ---------------------------------------------------
    def attach(self):
        """Claimed by ONE ClusterRouter run (mirrors the router's own
        run-once discipline: stale cooldowns/actions from a previous
        replay would silently skew the next one)."""
        if self._attached:
            raise RuntimeError("an Autoscaler drives one ClusterRouter "
                               "run — build a fresh one per replay")
        self._attached = True

    def standby_available(self) -> List[str]:
        return list(self._standby)

    def open_page_incidents(self) -> List[object]:
        """Still-open page incidents (a joiner's scheduler is told
        about these at join time, so it degrades like its peers)."""
        self._open_page = [i for i in self._open_page if i.open]
        return list(self._open_page)

    # --- the incident subscription (the on_incident/subscribe seam) --------
    def note_incident(self, inc) -> Optional[str]:
        """Called as each incident OPENS (the router appends this to
        the monitors' ``on_incident`` list). Tracks scale-worthy and
        page-severity incidents; returns ``"degrade"`` when the
        router should fan this incident into every live scheduler
        (page severity + ``cfg.degrade``), else None."""
        cfg = self.cfg
        sev_ok = SEVERITIES.index(inc.severity) \
            >= SEVERITIES.index(cfg.scale_severity)
        if inc.kind in cfg.scale_on and sev_ok:
            self._open_scale.append(inc)
        if not (cfg.degrade and inc.severity == "page"):
            return None
        self._open_page.append(inc)
        return "degrade"

    def log_degrade(self, inc):
        """The router's confirmation callback: the fan-out for ``inc``
        reached >= 1 live scheduler, so the degrade belongs in the
        action log (a fleet of FIFO engines actuates nothing, and the
        log must not claim otherwise). The log entry — not the
        actuation — is cooldown-capped, so an incident storm cannot
        flood the action log."""
        t = inc.t_open
        last = self._last["degrade"]
        if last is None \
                or t - last >= self.cfg.degrade_cooldown - 1e-12:
            self._last["degrade"] = t
            self.actions.append({"t": round(t, 6), "action": "degrade",
                                 "incident": inc.id, "rule": inc.rule})

    # --- the tick ----------------------------------------------------------
    def _cool(self, kind: str, t: float, span: float) -> bool:
        last = self._last[kind]
        return last is None or t - last >= span - 1e-12

    def decide(self, t: float, reps: Sequence, namer,
               sheds_total: int = 0) -> List[dict]:
        """One evaluation tick at virtual time ``t`` over the live
        replica set (the router's ``_Replica`` objects, duck-typed:
        ``name``/``role``/``admitting``/``index``/``session``).
        ``namer(base) -> unique replica name`` is the router's
        generation-suffix allocator; ``sheds_total`` the cluster-wide
        cumulative shed count (live sessions + banked results) — the
        loss signal that carries an armed scale-up episode, because
        an admission-shedding QoS front door converts overload into
        sheds, not slot saturation. Returns the actions the router
        must execute NOW, already appended to ``self.actions``."""
        cfg = self.cfg
        acts: List[dict] = []
        self._open_scale = [i for i in self._open_scale if i.open]
        self._open_page = [i for i in self._open_page if i.open]
        live = [r for r in reps if r.admitting]
        alive = [r for r in live if not r.session.crashed]

        # --- role rebalance (dedicated roles only) ----------------------
        if cfg.role_rebalance and self._cool("role", t, cfg.role_cooldown):
            act = self._decide_role(t, alive)
            if act is not None:
                self._last["role"] = t
                acts.append(act)

        # --- the load signals -------------------------------------------
        slots = sum(r.session.eng.slots for r in alive)
        busy = sum(r.session.eng.slots - r.session.free_slot_count()
                   for r in alive)
        frac = busy / slots if slots else 0.0
        shed_delta = max(0, sheds_total - self._last_sheds)
        self._last_sheds = sheds_total
        # arm on incident; disarm only after a sustained CALM window
        # (no loss, no open incident) — the episode outlives the one
        # incident that opened it
        if self._open_scale:
            self._armed = True
        if shed_delta or self._open_scale:
            self._calm_since = None
        elif self._calm_since is None:
            self._calm_since = t
        if self._armed and self._calm_since is not None \
                and t - self._calm_since >= cfg.recover_sustain - 1e-12:
            self._armed = False

        # --- scale up: a sustained burn opened the episode; ongoing
        # loss (sheds) or saturation carries it until the fleet
        # actually catches up ---------------------------------------------
        trigger = None
        if self._open_scale:
            trigger = "sustained_burn"
        elif self._armed and shed_delta:
            trigger = "armed_shedding"
        elif self._armed and frac >= cfg.join_above:
            trigger = "armed_saturation"
        # the max_replicas bound counts every non-crashed replica the
        # router still holds — a DRAINING replica (not admitting,
        # in-flight rows still streaming) keeps consuming slots and
        # pages, so it must block a join or the live fleet could
        # transiently exceed the bound
        occupying = sum(1 for r in reps if not r.session.crashed)
        if trigger is not None and self._standby \
                and self._cool("join", t, cfg.join_cooldown) \
                and self._cool("drain", t, cfg.hold_after_drain) \
                and (cfg.max_replicas is None
                     or occupying < cfg.max_replicas):
            base = self._standby.pop(0)
            name = namer(base)
            self._last["join"] = t
            self._low_since = None
            act = {"t": round(t, 6), "action": "join", "replica": name,
                   "base": base, "reason": trigger,
                   "busy_frac": round(frac, 4),
                   "incidents": [i.id for i in self._open_scale]}
            for inc in self._open_scale:
                inc.act(t, f"join:{name}")
            self._open_scale = []
            acts.append(act)

        # --- scale down: budget recovered + sustained low utilization ----
        if self._armed or self._open_page or shed_delta \
                or frac >= cfg.drain_below:
            self._low_since = None
        elif self._low_since is None:
            self._low_since = t
        if self._low_since is not None \
                and t - self._low_since >= cfg.drain_sustain - 1e-12 \
                and self._cool("drain", t, cfg.drain_cooldown) \
                and self._cool("join", t, cfg.hold_after_join) \
                and len(alive) > cfg.min_replicas:
            target = self._drain_target(live)
            if target is not None:
                self._last["drain"] = t
                if target.session.crashed:
                    # the drain decision landed on a replica that is
                    # mid-crash (silent, failover pending): a graceful
                    # drain is impossible and forcing one would race
                    # the failure detector — noop LOUDLY and let the
                    # failover own the removal. The drain cooldown is
                    # still charged so a dead replica cannot be
                    # "drained" again every tick.
                    acts.append({"t": round(t, 6),
                                 "action": "drain_noop_crashed",
                                 "replica": target.name,
                                 "reason": "mid-crash-failover"})
                else:
                    self._standby.append(target.name.split("#", 1)[0])
                    acts.append({"t": round(t, 6), "action": "drain",
                                 "replica": target.name,
                                 "reason": "budget_recovered_low_util",
                                 "busy_frac": round(frac, 4),
                                 "low_since": round(self._low_since,
                                                    6)})
        self.actions.extend(acts)
        return acts

    def _drain_target(self, live: Sequence):
        """The idlest admitting replica: least load, then fewest busy
        slots, then the LATEST-joined among equals (LIFO scale-down —
        the longest-lived replicas hold the warmest prefix caches).
        With dedicated roles, never the last prefill-capable or last
        decode-capable worker."""
        cands = list(live)
        roled = any(r.role != "both" for r in cands)
        if roled:
            pre = [r for r in cands if r.role in ("prefill", "both")]
            dec = [r for r in cands if r.role in ("decode", "both")]
            cands = [r for r in cands
                     if not (len(pre) <= 1 and r in pre)
                     and not (len(dec) <= 1 and r in dec)]
        if not cands:
            return None
        return min(cands, key=lambda r: (
            r.session.load(),
            r.session.eng.slots - r.session.free_slot_count(),
            -r.index))

    def _decide_role(self, t: float, alive: Sequence) -> Optional[dict]:
        cfg = self.cfg
        pre = [r for r in alive if r.role == "prefill"]
        dec = [r for r in alive if r.role == "decode"]
        if not pre or not dec:
            return None
        backlog = sum(r.session.prefill_backlog() for r in pre) \
            / len(pre)
        open_slots = sum(r.session.free_slot_count() for r in dec)
        if backlog >= cfg.prefill_hi and len(dec) >= 2 \
                and open_slots > 0:
            # prefill-starved: flip the decode worker with the most
            # open slots (it is the one decode misses least)
            r = min(dec, key=lambda x: (-x.session.free_slot_count(),
                                        x.session.load(), x.index))
            return {"t": round(t, 6), "action": "role",
                    "replica": r.name, "from": "decode",
                    "to": "prefill",
                    "reason": "prefill_backlog_high",
                    "backlog_per_prefill": round(backlog, 4)}
        if backlog <= cfg.prefill_lo and len(pre) >= 2 \
                and open_slots == 0:
            # decode-starved: flip the prefill worker with the least
            # pending work
            r = min(pre, key=lambda x: (x.session.prefill_backlog(),
                                        x.session.load(), x.index))
            return {"t": round(t, 6), "action": "role",
                    "replica": r.name, "from": "prefill",
                    "to": "decode", "reason": "decode_slots_exhausted",
                    "backlog_per_prefill": round(backlog, 4)}
        return None

    # --- rollup ------------------------------------------------------------
    def summary(self) -> dict:
        """The ``ClusterResult.autoscale`` block: the full action log
        plus per-kind counts and the standby pool that remains."""
        by: dict = {}
        for a in self.actions:
            by[a["action"]] = by.get(a["action"], 0) + 1
        return {"actions": list(self.actions),
                "joins": by.get("join", 0),
                "drains": by.get("drain", 0),
                "drain_noops": by.get("drain_noop_crashed", 0),
                "role_changes": by.get("role", 0),
                "degrades": by.get("degrade", 0),
                "standby_left": list(self._standby)}


def count_oscillations(actions: Sequence[dict], window: float) -> int:
    """The oscillation audit the ``serving_autoscale`` gate runs: a
    join at ``t`` followed by ANY drain within ``window`` units is one
    oscillation (capacity added then immediately taken away — the
    thrash hysteresis exists to forbid). Zero on a healthy log."""
    joins = [a["t"] for a in actions if a["action"] == "join"]
    drains = [a["t"] for a in actions if a["action"] == "drain"]
    return sum(1 for tj in joins for td in drains
               if 0.0 <= td - tj < window)
