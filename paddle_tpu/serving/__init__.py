"""paddle_tpu.serving — continuous-batching serving engine + workload
harness.

The subsystem above ``models/nlp/llama_decode`` and ``inference``: a
request-stream engine (``ServingEngine``) driving the dense compiled
cache and the paged KV pool behind a pluggable routing policy, a
seeded replayable trace generator (``workload``), and per-request
TTFT/TPOT/SLO metrics (``metrics``). ``tools/serving_workload_bench.py``
replays one trace through routed / dense-only / paged-only and
``tools/bench_gate.py serving`` gates the routed row.
"""
from .engine import (EngineClock, FixedPolicy,  # noqa: F401
                     Policy, RoutedPolicy, ServeResult, ServingEngine,
                     make_policy)
from .metrics import MetricsCollector  # noqa: F401
from .workload import (Request, load_trace, merge_traces,  # noqa: F401
                       save_trace, synthesize_trace, trace_stats)
