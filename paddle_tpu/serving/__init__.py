"""paddle_tpu.serving — continuous-batching serving engine + workload
harness.

The subsystem above ``models/nlp/llama_decode`` and ``inference``: a
request-stream engine (``ServingEngine``) driving the dense compiled
cache and the paged KV pool behind a pluggable routing policy, a QoS
scheduling front door (``scheduler.QoSScheduler``: strict priorities
over per-tenant weighted fair queueing, deadline-feasibility
admission, overload shedding + degradation tiers), a multi-replica
cluster router (``cluster.ClusterRouter``: round_robin /
least_loaded / prefix_aware placement over N ``EngineSession``
replicas on one shared virtual timeline, drain/join lifecycle,
rollup goodput/fairness metrics; ``sim.make_sim_serving`` scales its
gate to 10^5 requests), a fault-tolerance layer (``faults``: seeded
replayable crash/stall/decode-error plans, heartbeat failure
detection, failover with retry budgets + resume-from-prefix — the
``--chaos`` arm gates zero lost/duplicated requests and token parity
vs fault-free), a multi-model LoRA layer
(``adapters``: host-resident ``AdapterStore`` + budgeted
``AdapterCache`` paging delta sets into the device bank the compiled
fixed-shape decode batch reads per row — thousands of fine-tuned
variants of one base model from one engine, ``--lora`` gates
multiplexed goodput >= 1.2x a one-model-per-replica split), a
constrained-decoding layer (``grammar``: JSON-schema / EBNF sources
compiled host-side into token-level DFAs whose packed allow-bitmasks
live in a budgeted device bank — ``GrammarStore`` + ``GrammarCache``
— so one fixed-shape decode batch mixes schema-locked and free rows,
``--grammar`` gates 100% parse at >= 0.95x unconstrained
throughput), a seeded replayable trace generator
(``workload``, including the multi-tenant overload, cluster and
Zipf-adapter traces), and per-request TTFT/TPOT/SLO/goodput/fairness
metrics (``metrics``). The whole stack is watchable by the SLO layer
(``paddle_tpu.obs.slo``/``obs.flight``): ``ServingEngine(slo=...)``
and ``ClusterRouter(slo=..., flight=...)`` evaluate burn-rate /
threshold / heartbeat rules streaming on the virtual clock and
freeze postmortem bundles per incident, without changing a byte of
output. ``tools/serving_workload_bench.py`` replays one trace
through routed / dense-only / paged-only (``--qos`` replays the
overload trace fifo-vs-qos, ``--cluster`` the 10^5-request trace
across placements, ``--chaos``/``--slo`` the seeded fault schedule);
``tools/bench_gate.py serving``/``obs`` gate every family.
"""
from .adapters import AdapterCache, AdapterStore  # noqa: F401
from .autoscale import (AutoscaleConfig, Autoscaler,  # noqa: F401
                        count_oscillations)
from .cluster import (ClusterResult, ClusterRouter,  # noqa: F401
                      DisaggregatedPlacement, LeastLoadedPlacement,
                      PlacementPolicy, PrefixAwarePlacement,
                      RoundRobinPlacement, make_placement)
from ..models.nlp.llama_decode import (GrammarConfig,  # noqa: F401
                                       LoRAConfig, SpecConfig,
                                       TPConfig,
                                       synthesize_lora_deltas)
from .grammar import (CompiledGrammar, GrammarCache,  # noqa: F401
                      GrammarStore, TokenVocab, compile_grammar,
                      compile_schema, compile_source, schema_accepts)
from .engine import (DecodeError, EngineClock,  # noqa: F401
                     EngineSession, FixedPolicy, KVHandoff, Policy,
                     RoutedPolicy, ServeResult, ServingEngine,
                     UnstampedHandoffError, load_engine_log,
                     make_policy)
from .faults import (FailoverConfig, FaultEvent,  # noqa: F401
                     FaultPlan, synthesize_fault_plan)
from .hostmem import (HostArena, HostMemConfig,  # noqa: F401
                      as_hostmem_config)
from .metrics import (MetricsCollector, goodput_tokens,  # noqa: F401
                      jain_fairness)
from .scheduler import (QoSScheduler, SchedDecision,  # noqa: F401
                        ServiceEstimator)
from .sim import SimServing, make_sim_serving  # noqa: F401
from .workload import (DEFAULT_TENANTS, Request,  # noqa: F401
                       load_trace, merge_traces, save_trace,
                       synthesize_admission_burst_trace,
                       synthesize_cluster_trace,
                       synthesize_deadline_mix_trace,
                       synthesize_diurnal_trace,
                       synthesize_flash_crowd_trace,
                       synthesize_overload_trace,
                       synthesize_prefill_heavy_trace,
                       synthesize_recurring_prefix_trace,
                       synthesize_schema_trace,
                       synthesize_session_trace,
                       synthesize_trace,
                       synthesize_zipf_adapter_trace, trace_stats)
