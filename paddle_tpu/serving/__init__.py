"""paddle_tpu.serving — continuous-batching serving engine + workload
harness.

The subsystem above ``models/nlp/llama_decode`` and ``inference``: a
request-stream engine (``ServingEngine``) driving the dense compiled
cache and the paged KV pool behind a pluggable routing policy, a QoS
scheduling front door (``scheduler.QoSScheduler``: strict priorities
over per-tenant weighted fair queueing, deadline-feasibility
admission, overload shedding + degradation tiers), a seeded
replayable trace generator (``workload``, including the multi-tenant
overload trace), and per-request TTFT/TPOT/SLO/goodput/fairness
metrics (``metrics``). ``tools/serving_workload_bench.py`` replays
one trace through routed / dense-only / paged-only (and ``--qos``
replays the overload trace fifo-vs-qos); ``tools/bench_gate.py
serving`` gates both families.
"""
from .engine import (EngineClock, FixedPolicy,  # noqa: F401
                     Policy, RoutedPolicy, ServeResult, ServingEngine,
                     load_engine_log, make_policy)
from .metrics import MetricsCollector  # noqa: F401
from .scheduler import (QoSScheduler, SchedDecision,  # noqa: F401
                        ServiceEstimator)
from .workload import (DEFAULT_TENANTS, Request,  # noqa: F401
                       load_trace, merge_traces, save_trace,
                       synthesize_overload_trace,
                       synthesize_recurring_prefix_trace,
                       synthesize_trace, trace_stats)
