"""Serving metrics: per-request latency decomposition + stream rates.

The numbers a serving system is judged by, none of which a per-shape
microbench can produce:

- **TTFT** (time to first token): arrival -> first generated token.
  Queueing + admission + prefill; the interactive-feel metric.
- **TPOT** (time per output token): mean inter-token gap after the
  first token. The streaming-rate metric; stalls (e.g. a dense wave
  hogging the chip) show up here, not in TTFT.
- **p50/p95** over requests, not tokens — tail latency is what SLOs
  bind on.
- **SLO attainment**: fraction of completed requests whose TTFT/TPOT
  beat the target.

``MetricsCollector`` ingests engine events with the engine's (virtual)
clock timestamps and exports one PERF-style JSON record per run.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class _Req:
    __slots__ = ("arrival", "admit", "backend", "token_times", "n_tokens",
                 "finish", "evicted")

    def __init__(self, arrival: float):
        self.arrival = arrival
        self.admit: Optional[float] = None
        self.backend: Optional[str] = None
        self.token_times: List[float] = []  # one stamp per token
        self.n_tokens = 0
        self.finish: Optional[float] = None
        self.evicted = False


def _pct(xs, q) -> Optional[float]:
    return round(float(np.percentile(np.asarray(xs), q)), 6) if xs \
        else None


class MetricsCollector:
    """Event sink for one engine run; all timestamps come from the
    engine clock (wall-measured or fixed-cost — the collector does not
    care which)."""

    def __init__(self):
        self._req: Dict[str, _Req] = {}
        self._queue: List[tuple] = []  # (t, depth)

    # --- events ----------------------------------------------------------
    def on_arrival(self, rid: str, t: float):
        self._req[rid] = _Req(t)

    def on_admit(self, rid: str, t: float, backend: str):
        r = self._req[rid]
        r.admit = t
        r.backend = backend

    def on_tokens(self, rid: str, t: float, n: int):
        """``n`` tokens materialized at time ``t`` (a decode chunk's
        tokens share one stamp — TPOT is chunk-granular by design)."""
        r = self._req[rid]
        r.token_times.extend([t] * n)
        r.n_tokens += n

    def on_finish(self, rid: str, t: float, evicted: bool = False):
        r = self._req[rid]
        r.finish = t
        r.evicted = evicted

    def on_queue_depth(self, t: float, depth: int):
        self._queue.append((t, depth))

    # --- views -----------------------------------------------------------
    def request(self, rid: str) -> dict:
        r = self._req[rid]
        ttft = (r.token_times[0] - r.arrival) if r.token_times else None
        tpot = None
        if len(r.token_times) > 1:
            tpot = ((r.token_times[-1] - r.token_times[0])
                    / (len(r.token_times) - 1))
        return {"arrival": r.arrival, "admit": r.admit,
                "backend": r.backend, "n_tokens": r.n_tokens,
                "finish": r.finish, "evicted": r.evicted,
                "ttft": ttft, "tpot": tpot,
                "e2e": (r.finish - r.arrival)
                if r.finish is not None else None}

    def report(self, slo_ttft: Optional[float] = None,
               slo_tpot: Optional[float] = None) -> dict:
        """Aggregate over FINISHED requests (evictions included: a
        canceled request still had a TTFT and a streaming rate while it
        lived)."""
        done = [self.request(rid) for rid in self._req
                if self._req[rid].finish is not None]
        ttfts = [d["ttft"] for d in done if d["ttft"] is not None]
        tpots = [d["tpot"] for d in done if d["tpot"] is not None]
        e2es = [d["e2e"] for d in done]
        tokens = sum(d["n_tokens"] for d in done)
        arrivals = [r.arrival for r in self._req.values()]
        finishes = [r.finish for r in self._req.values()
                    if r.finish is not None]
        makespan = (max(finishes) - min(arrivals)) \
            if finishes and arrivals else 0.0
        depths = [d for _, d in self._queue]
        rec = {
            "completed": len(done),
            "evicted": sum(1 for d in done if d["evicted"]),
            "generated_tokens": tokens,
            "makespan": round(makespan, 6),
            "tokens_per_sec": round(tokens / makespan, 4)
            if makespan > 0 else None,
            "ttft_p50": _pct(ttfts, 50), "ttft_p95": _pct(ttfts, 95),
            "tpot_p50": _pct(tpots, 50), "tpot_p95": _pct(tpots, 95),
            "e2e_p50": _pct(e2es, 50), "e2e_p95": _pct(e2es, 95),
            "queue_depth_max": max(depths) if depths else 0,
            "queue_depth_mean": round(float(np.mean(depths)), 3)
            if depths else 0.0,
        }
        if slo_ttft is not None and ttfts:
            rec["slo_ttft"] = slo_ttft
            rec["slo_ttft_attained"] = round(
                sum(1 for x in ttfts if x <= slo_ttft) / len(ttfts), 4)
        if slo_tpot is not None and tpots:
            rec["slo_tpot"] = slo_tpot
            rec["slo_tpot_attained"] = round(
                sum(1 for x in tpots if x <= slo_tpot) / len(tpots), 4)
        return rec

    def to_record(self, policy: str, **extra) -> dict:
        """The canonical ``serving_workload`` row
        (tools/serving_workload_bench.py emits one per policy;
        tools/bench_gate.py serving mode gates routed vs best fixed)."""
        rec = {"bench": "serving_workload", "policy": policy}
        rec.update(self.report(**{k: extra.pop(k) for k in
                                  ("slo_ttft", "slo_tpot")
                                  if k in extra}))
        rec.update(extra)
        return rec
