"""Serving metrics: per-request latency decomposition + stream rates.

The numbers a serving system is judged by, none of which a per-shape
microbench can produce:

- **TTFT** (time to first token): arrival -> first generated token.
  Queueing + admission + prefill; the interactive-feel metric.
- **TPOT** (time per output token): mean inter-token gap after the
  first token. The streaming-rate metric; stalls (e.g. a dense wave
  hogging the chip) show up here, not in TTFT.
- **p50/p95** over requests, not tokens — tail latency is what SLOs
  bind on.
- **SLO attainment**: fraction of completed requests whose TTFT/TPOT
  beat the target.

``MetricsCollector`` ingests engine events with the engine's (virtual)
clock timestamps and exports one PERF-style JSON record per run.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class _Req:
    __slots__ = ("arrival", "admit", "backend", "token_times", "n_tokens",
                 "finish", "evicted", "tenant", "priority", "deadline_ms",
                 "shed", "shed_reason", "budget0", "budget",
                 "finish_reason")

    def __init__(self, arrival: float, tenant=None, priority=0,
                 deadline_ms=None):
        self.arrival = arrival
        self.admit: Optional[float] = None
        self.backend: Optional[str] = None
        self.token_times: List[float] = []  # one stamp per token
        self.n_tokens = 0
        self.finish: Optional[float] = None
        self.evicted = False
        self.tenant: Optional[str] = tenant
        self.priority = priority
        self.deadline_ms: Optional[float] = deadline_ms
        self.shed = False
        self.shed_reason: Optional[str] = None
        self.budget0: Optional[int] = None  # pre-degradation budget
        self.budget: Optional[int] = None   # admitted (clamped) budget
        self.finish_reason: Optional[str] = None


def percentile(xs, q) -> Optional[float]:
    """THE percentile used by every serving report path (request
    latencies in ``MetricsCollector.report``, the cluster rollup, the
    bench rows) — one implementation so two reports can never disagree
    on the arithmetic. Linear interpolation between closest ranks
    (numpy's default), rounded to 6 places. Small-n semantics are
    DEFINED, not accidental:

    - ``n == 0``: ``None`` (a percentile of nothing is not 0.0);
    - ``n == 1``: the value itself, for every ``q``;
    - ``n == 2``: linear interpolation — ``q=50`` is the midpoint,
      ``q=95`` sits 90% of the way to the larger value.
    """
    if xs is None or len(xs) == 0:
        return None
    return round(float(np.percentile(np.asarray(xs), q)), 6)


# internal alias predating the public name; kept so call sites read
# compactly in report-building code
_pct = percentile


def jain_fairness(xs) -> Optional[float]:
    """Jain's fairness index over per-tenant allocations (typically
    weight-normalized goodput): ``(sum x)^2 / (n * sum x^2)``. 1.0 is
    perfectly fair, ``1/n`` is one tenant taking everything. Returns
    None when every allocation is zero (the index is undefined, not
    unfair). ONE implementation shared by the per-run QoS block and the
    cluster rollup — the two can never disagree on the arithmetic."""
    xs = [float(x) for x in xs]
    sq = sum(x * x for x in xs)
    if sq <= 0 or not xs:
        return None
    return round((sum(xs) ** 2) / (len(xs) * sq), 4)


def goodput_tokens(views) -> int:
    """Goodput over request views (``MetricsCollector.request`` dicts):
    tokens from SLO-met requests ONLY — a shed, late, or evicted
    request contributes nothing. Shared by the per-run QoS block and
    the cluster rollup."""
    return sum(int(v["n_tokens"]) for v in views if v["deadline_met"])


class MetricsCollector:
    """Event sink for one engine run; all timestamps come from the
    engine clock (wall-measured or fixed-cost — the collector does not
    care which)."""

    def __init__(self, monitor=None):
        self._req: Dict[str, _Req] = {}
        self._queue: List[tuple] = []  # (t, depth)
        # prefix-cache totals over paged admits (engine-fed); the
        # report grows its prefix block only when a hit happened, so
        # plain no-hit traces stay byte-identical
        self._prefix = {"cached": 0, "saved": 0, "prompt": 0}
        # per-device pool bytes (tensor-parallel runs only): kept so
        # publish() can export the sharded-only gauge; None = never
        # sharded, nothing exported (PR-5 convention)
        self._pool_dev_bytes: Optional[int] = None
        # adapter-cache totals over multi-model admits (engine-fed);
        # the report grows its adapter block ONLY when an adapter
        # request was actually served, so single-model traces stay
        # byte-identical (the PR-5 hits>0 convention)
        self._adapter = {"requests": 0, "hits": 0, "uploads": 0}
        self._adapter_names: set = set()
        self._adapter_resident: Optional[int] = None
        # speculative-route totals (engine-fed per spec turn); the
        # report grows its spec block ONLY when a spec round actually
        # ran, so plain traces — and spec=None replays — keep their
        # records byte-identical (the PR-5 presence convention)
        self._spec = {"rounds": 0, "proposed": 0, "accepted": 0}
        # per-tenant cost-ledger snapshot (engine-fed at run end via
        # ``note_costs`` ONLY when a ledger is armed); the per-tenant
        # report block grows its cost columns only then, so ledger-off
        # reports stay byte-identical (the PR-5 presence convention)
        self._tenant_costs: Optional[Dict[str, dict]] = None
        # quantized-page-tier totals (engine-fed); the report grows
        # its kv_quant block ONLY when a quantized mode is armed, so
        # kv_quant=None runs keep their records byte-identical (the
        # PR-5 presence convention)
        self._kv_quant = {"mode": None, "flips": 0, "compactions": 0,
                          "pages": 0}
        # host-arena tier totals (engine-fed); the report grows its
        # hostmem block ONLY when a page actually crossed the tier
        # boundary or a preemption fired, so hostmem=None runs keep
        # their records byte-identical (the PR-5 presence convention)
        self._hostmem = {"pageouts": 0, "pageins": 0,
                         "preempts": 0, "restores": 0}
        # constrained-decoding totals (engine-fed); the report grows
        # its grammar block ONLY when a constrained row actually ran,
        # so grammar=None runs keep their records byte-identical (the
        # PR-5 presence convention)
        self._grammar = {"streams": 0, "hits": 0, "compiles": 0,
                        "tokens": 0, "masked_sum": 0.0, "accepts": 0}
        self._grammar_names: set = set()
        # ``monitor`` (obs.slo.SLOMonitor, optional) receives each
        # request's FINAL record at finish/shed plus queue/lane depth
        # samples — the one seam through which the streaming SLO layer
        # sees everything the collector sees. It only READS: with a
        # monitor attached or not, every record/report/output byte is
        # identical (the obs_slo gate measures exactly this).
        self._mon = monitor

    # --- events ----------------------------------------------------------
    def on_arrival(self, rid: str, t: float, tenant: Optional[str] = None,
                   priority: int = 0,
                   deadline_ms: Optional[float] = None):
        self._req[rid] = _Req(t, tenant=tenant, priority=priority,
                              deadline_ms=deadline_ms)

    def on_admit(self, rid: str, t: float, backend: str):
        r = self._req[rid]
        r.admit = t
        r.backend = backend

    def on_shed(self, rid: str, t: float, reason: str):
        """The scheduler rejected ``rid`` (queue bound or deadline
        infeasibility) — it never runs, never finishes, and can never
        count as an SLO hit."""
        r = self._req[rid]
        r.shed = True
        r.shed_reason = reason
        r.finish_reason = "shed"
        if self._mon is not None:
            self._mon.observe_request(dict(self.request(rid), rid=rid),
                                      t)

    def on_degrade(self, rid: str, budget: int, orig_budget: int):
        """Graceful-degradation tier fired: ``rid`` was admitted with
        ``max_new_tokens`` clamped from ``orig_budget`` to ``budget``."""
        r = self._req[rid]
        r.budget = budget
        r.budget0 = orig_budget

    def on_prefix(self, rid: str, cached: int, saved: int, prompt: int):
        """``rid`` admitted to the paged backend with ``cached`` of its
        ``prompt`` tokens found in the prefix cache, of which ``saved``
        (chunk-aligned) actually skipped prefill compute."""
        self._prefix["cached"] += cached
        self._prefix["saved"] += saved
        self._prefix["prompt"] += prompt

    def on_tokens(self, rid: str, t: float, n: int):
        """``n`` tokens materialized at time ``t`` (a decode chunk's
        tokens share one stamp — TPOT is chunk-granular by design)."""
        r = self._req[rid]
        r.token_times.extend([t] * n)
        r.n_tokens += n

    def on_finish(self, rid: str, t: float, evicted: bool = False,
                  reason: Optional[str] = None):
        r = self._req[rid]
        r.finish = t
        r.evicted = evicted
        if reason is not None:
            r.finish_reason = reason
        if self._mon is not None:
            self._mon.observe_request(dict(self.request(rid), rid=rid),
                                      t)

    def on_queue_depth(self, t: float, depth: int):
        self._queue.append((t, depth))
        if self._mon is not None:
            self._mon.observe_value("queue_depth", depth, t)

    def on_lane_depth(self, t: float, depth: int):
        """Async-prefill-lane depth sample. Stored nowhere (the lane
        gauge already exports it live); exists purely to stream the
        signal to an attached SLO monitor — a no-op without one, so
        pre-SLO replays are untouched."""
        if self._mon is not None:
            self._mon.observe_value("prefill_lane_depth", depth, t)

    def on_busy_frac(self, t: float, frac: float):
        """Decode-slot utilization sample (busy slots / capacity,
        engine-fed once per turn). Stored nowhere (the
        ``serving_replica_busy_frac`` gauge exports it live); exists
        to stream the signal to an attached SLO monitor — the drain-
        decision input, watchable via ``ThresholdRule(signal=
        "replica_busy_frac", op="<=", ...)`` like any gauge sample.
        A no-op without a monitor, so pre-SLO replays are
        untouched."""
        if self._mon is not None:
            self._mon.observe_value("replica_busy_frac", frac, t)

    def on_adapter(self, rid: str, adapter: str, hit: bool):
        """``rid`` admitted decoding with LoRA ``adapter``; ``hit``
        means the delta set was already resident in the device bank
        (a miss paid one paced host->device upload)."""
        self._adapter["requests"] += 1
        self._adapter["hits" if hit else "uploads"] += 1
        self._adapter_names.add(adapter)

    def on_adapter_resident(self, t: float, count: int):
        """Resident-adapter census sample (pinned + retained slots,
        engine-fed on every acquire/release). Kept for publish()'s
        gauge and streamed to an attached SLO monitor so a
        ``ThresholdRule(signal="adapter_resident")`` can watch bank
        pressure; a no-op single-model."""
        self._adapter_resident = int(count)
        if self._mon is not None:
            self._mon.observe_value("adapter_resident", count, t)

    def on_spec(self, rows: int, proposed: int, accepted: int):
        """One speculative decode turn: ``rows`` rows each ran one
        draft/verify round, ``proposed`` draft tokens went to target
        verification, ``accepted`` survived it. Wasted draft compute
        is the difference — the number the adaptive fallback exists
        to bound."""
        self._spec["rounds"] += rows
        self._spec["proposed"] += proposed
        self._spec["accepted"] += accepted

    def note_costs(self, per_tenant: Dict[str, dict]):
        """Engine-fed at run end, ONLY when a cost ledger is armed:
        ``CostLedger.tenant_costs()`` — tenant -> {cost_units,
        page_turns}. The per-tenant report block grows its two cost
        columns only for tenants present here; un-armed runs never
        call this and their reports stay byte-identical."""
        self._tenant_costs = dict(per_tenant)

    def on_pool_bytes(self, t: float, per_device_bytes: int):
        """Per-device KV-pool residency sample (tensor-parallel
        engines only — unsharded runs never call this). Stored
        nowhere (the serving_pool_bytes_per_device gauge exports it
        live); exists to stream the signal to an attached SLO monitor
        so a ``ThresholdRule(signal="pool_bytes_per_device", ...)``
        can watch per-device HBM pressure."""
        self._pool_dev_bytes = int(per_device_bytes)
        if self._mon is not None:
            self._mon.observe_value("pool_bytes_per_device",
                                    per_device_bytes, t)

    def on_kv_quant(self, mode: str):
        """A quantized page tier is armed for this run (``"int8"`` or
        ``"pressure"``): the report grows its kv_quant block. Called
        once by the engine at run setup."""
        self._kv_quant["mode"] = mode

    def on_kv_quant_flip(self, enabled: bool):
        """The pressure tier flipped (on or off) — one deterministic
        actuation of the pool-byte incident."""
        self._kv_quant["flips"] += 1

    def on_compaction(self, t: float, pages: int):
        """One compaction batch: ``pages`` parked pages quantized to
        int8 (their prefix keys intact — nothing was forgotten)."""
        self._kv_quant["compactions"] += 1
        self._kv_quant["pages"] += int(pages)

    def on_pageout(self, t: float, pages: int):
        """``pages`` device pages spilled to the host arena (eviction
        spill or a preemption swap-out) — each paid one priced
        ``kv_pageout`` transfer on the engine clock."""
        self._hostmem["pageouts"] += int(pages)

    def on_pagein(self, t: float, pages: int):
        """``pages`` arena pages restored into the device pool at
        admission (a prefix hit on a spilled chain, or a preempted
        request swapping back in) — each paid one priced
        ``kv_pagein`` transfer."""
        self._hostmem["pageins"] += int(pages)

    def on_preempt(self, rid: str, t: float, emitted: int):
        """The QoS preempt rung fired: running row ``rid`` (with
        ``emitted`` tokens already streamed) swapped its chain out to
        the host arena and requeued — capacity surrendered to a
        higher class WITHOUT discarding the work."""
        self._hostmem["preempts"] += 1

    def on_restore(self, rid: str, t: float):
        """A preempted request re-admitted: its swapped chain paged
        back in (or re-prefilled where the arena had let go) and its
        stream resumes exactly where it stopped."""
        self._hostmem["restores"] += 1

    def on_grammar(self, rid: str, schema: str, hit: bool):
        """``rid`` admitted as a CONSTRAINED stream under ``schema``;
        ``hit`` means the compiled automaton was already resident in
        the device mask bank (a miss paid one priced
        ``grammar_compile`` on the engine clock)."""
        self._grammar["streams"] += 1
        self._grammar["hits" if hit else "compiles"] += 1
        self._grammar_names.add(schema)

    def on_grammar_tokens(self, n: int, masked_frac_sum: float):
        """``n`` constrained tokens emitted under grammar masks whose
        per-token forbidden-vocab fractions sum to
        ``masked_frac_sum`` — the report's ``tokens_masked_frac`` is
        the mean, how much of the vocabulary the automaton actually
        pruned per step."""
        self._grammar["tokens"] += int(n)
        self._grammar["masked_sum"] += float(masked_frac_sum)

    def on_grammar_accept(self, rid: str, t: float):
        """``rid``'s automaton reached an accepting state and the
        stream self-terminated — structurally complete output, before
        (or at) its token budget."""
        self._grammar["accepts"] += 1

    def forget(self, rid: str):
        """Erase every trace of ``rid`` from this collector — the
        cluster router's requeue/failover path: a request moving off a
        drained replica's queue, or off a CRASHED replica (queued or
        torn down mid-flight), is re-recorded in full wherever it
        finally runs, sheds, or exhausts its retry budget; keeping the
        arrival here would count the request twice in any cluster-wide
        rollup. This is one half of the exactly-once contract the
        cluster census gates (``completed + shed + failed ==
        arrived``)."""
        self._req.pop(rid, None)

    # --- views -----------------------------------------------------------
    def request_rows(self) -> List[dict]:
        """Every request's view (``request()`` dict plus its ``rid``),
        arrival-ordered — the public surface a cluster rollup
        aggregates across replicas."""
        return [dict(self.request(rid), rid=rid)
                for rid in sorted(self._req,
                                  key=lambda r: (self._req[r].arrival,
                                                 r))]

    def request(self, rid: str) -> dict:
        r = self._req[rid]
        ttft = (r.token_times[0] - r.arrival) if r.token_times else None
        tpot = None
        if len(r.token_times) > 1:
            tpot = ((r.token_times[-1] - r.token_times[0])
                    / (len(r.token_times) - 1))
        # the end-to-end decomposition disaggregation is judged on:
        # queue_wait (arrival -> admit), prefill_stall (admit -> first
        # token: the prefill itself plus any async-lane wait), and
        # decode_time (first token -> finish). decode_stall is the
        # worst inter-token gap IN EXCESS of the stream's own best
        # steady rate (worst positive gap minus best positive gap): an
        # uninterrupted stream scores 0.0, and what a co-scheduled
        # long prefill does to a live stream in an interleaved loop
        # shows up here as exactly the turns it stole
        queue_wait = (r.admit - r.arrival) if r.admit is not None \
            else None
        prefill_stall = (r.token_times[0] - r.admit) \
            if r.token_times and r.admit is not None else None
        decode_time = (r.finish - r.token_times[0]) \
            if r.finish is not None and r.token_times else None
        gaps = [b - a for a, b in zip(r.token_times, r.token_times[1:])
                if b - a > 1e-12]
        stall = (max(gaps) - min(gaps)) if gaps else \
            (0.0 if len(r.token_times) > 1 else None)
        d = {"arrival": r.arrival, "admit": r.admit,
             "backend": r.backend, "n_tokens": r.n_tokens,
             "finish": r.finish, "evicted": r.evicted,
             "ttft": ttft, "tpot": tpot,
             "e2e": (r.finish - r.arrival)
             if r.finish is not None else None,
             "queue_wait": queue_wait,
             "prefill_stall": prefill_stall,
             "decode_time": decode_time,
             "decode_stall": stall,
             "tenant": r.tenant, "priority": r.priority,
             "deadline_ms": r.deadline_ms, "shed": r.shed,
             "shed_reason": r.shed_reason,
             "finish_reason": r.finish_reason,
             "degraded_from": r.budget0}
        # SLO verdict: a shed request is NEVER met; without a deadline,
        # finishing UN-EVICTED counts as met (a canceled/timed-out
        # stream delivered partial work, not an SLO-met answer)
        if r.shed:
            d["deadline_met"] = False
        elif r.finish is None:
            d["deadline_met"] = None
        elif r.deadline_ms is None:
            d["deadline_met"] = not r.evicted
        else:
            d["deadline_met"] = bool(
                (r.finish - r.arrival) * 1000.0
                <= r.deadline_ms + 1e-6)
        return d

    def report(self, slo_ttft: Optional[float] = None,
               slo_tpot: Optional[float] = None,
               tenant_weights: Optional[Dict[str, float]] = None) -> dict:
        """Aggregate over FINISHED requests (evictions included: a
        canceled request still had a TTFT and a streaming rate while it
        lived). When the run carried QoS traffic (tenants, deadlines,
        or sheds), the record grows the QoS block — shed rate, deadline
        attainment, goodput (tokens from SLO-met requests ONLY; a shed
        or late request contributes nothing), per-tenant rows and the
        Jain fairness index over weight-normalized tenant goodput.
        Plain traces keep the PR-2 record byte-for-byte."""
        done = [self.request(rid) for rid in self._req
                if self._req[rid].finish is not None]
        ttfts = [d["ttft"] for d in done if d["ttft"] is not None]
        tpots = [d["tpot"] for d in done if d["tpot"] is not None]
        e2es = [d["e2e"] for d in done]
        tokens = sum(d["n_tokens"] for d in done)
        arrivals = [r.arrival for r in self._req.values()]
        finishes = [r.finish for r in self._req.values()
                    if r.finish is not None]
        makespan = (max(finishes) - min(arrivals)) \
            if finishes and arrivals else 0.0
        depths = [d for _, d in self._queue]
        rec = {
            "completed": len(done),
            "evicted": sum(1 for d in done if d["evicted"]),
            "generated_tokens": tokens,
            "makespan": round(makespan, 6),
            "tokens_per_sec": round(tokens / makespan, 4)
            if makespan > 0 else None,
            "ttft_p50": _pct(ttfts, 50), "ttft_p95": _pct(ttfts, 95),
            "tpot_p50": _pct(tpots, 50), "tpot_p95": _pct(tpots, 95),
            "e2e_p50": _pct(e2es, 50), "e2e_p95": _pct(e2es, 95),
            "queue_depth_max": max(depths) if depths else 0,
            "queue_depth_mean": round(float(np.mean(depths)), 3)
            if depths else 0.0,
        }
        # per-request latency DECOMPOSED: where did the e2e go —
        # queueing (arrival->admit), prefill stall (admit->first
        # token, async-lane wait included) or decode (first
        # token->finish)? The disaggregation claims are judged on
        # exactly this split.
        for key, field in (("queue_wait", "queue_wait"),
                           ("prefill_stall", "prefill_stall"),
                           ("decode_time", "decode_time")):
            xs = [d[field] for d in done if d[field] is not None]
            rec[f"{key}_p50"] = _pct(xs, 50)
            rec[f"{key}_p95"] = _pct(xs, 95)
        if self._prefix["cached"] > 0:
            # the prefix block appears ONLY when the cache actually hit
            # — a plain no-hit trace keeps the PR-4 record byte-for-byte
            rec["prefix_cache_hit_tokens"] = self._prefix["cached"]
            rec["prefix_cache_hit_rate"] = round(
                self._prefix["cached"] / max(1, self._prefix["prompt"]),
                4)
            rec["prefill_tokens_saved"] = self._prefix["saved"]
        if self._adapter["requests"] > 0:
            # the adapter block appears ONLY when the trace actually
            # carried adapters (the same convention): single-model
            # records stay byte-identical to PR 11
            rec["adapter_requests"] = self._adapter["requests"]
            rec["adapters_served"] = len(self._adapter_names)
            rec["adapter_cache_hits"] = self._adapter["hits"]
            rec["adapter_uploads"] = self._adapter["uploads"]
            rec["adapter_cache_hit_rate"] = round(
                self._adapter["hits"] / self._adapter["requests"], 4)
            if self._adapter_resident is not None:
                rec["adapters_resident_end"] = self._adapter_resident
        if self._spec["rounds"] > 0:
            # the spec block appears ONLY when a spec route actually
            # ran (the same convention): plain records — and any
            # spec=None replay — stay byte-identical to PR 12
            rec["spec_rounds"] = self._spec["rounds"]
            rec["spec_acceptance_rate"] = round(
                self._spec["accepted"] / max(1, self._spec["proposed"]),
                4)
            rec["draft_tokens_proposed"] = self._spec["proposed"]
            rec["draft_tokens_wasted"] = (self._spec["proposed"]
                                          - self._spec["accepted"])
        if self._kv_quant["mode"] is not None:
            # quantized-page-tier block, present only when a kv_quant
            # mode is armed (same convention): kv_quant=None replays
            # stay byte-identical to PR 14
            rec["kv_quant"] = self._kv_quant["mode"]
            rec["kv_quant_flips"] = self._kv_quant["flips"]
            rec["kv_compactions"] = self._kv_quant["compactions"]
            rec["kv_pages_compacted"] = self._kv_quant["pages"]
            if self._pool_dev_bytes is not None:
                # the dynamic stored-bytes census the pressure rule
                # watches (actual stored: quantized pages priced at
                # int8+scale size)
                rec["pool_bytes_per_device"] = self._pool_dev_bytes
        if any(self._hostmem.values()):
            # host-arena tier block, present only when a page actually
            # crossed the tier boundary or a preemption fired (same
            # convention): hostmem=None replays stay byte-identical
            rec["kv_pageouts"] = self._hostmem["pageouts"]
            rec["kv_pageins"] = self._hostmem["pageins"]
            rec["preemptions"] = self._hostmem["preempts"]
            rec["preempt_restores"] = self._hostmem["restores"]
        if self._grammar["streams"] > 0:
            # constrained-decoding block, present only when a
            # constrained row actually ran (same convention):
            # grammar=None replays stay byte-identical
            rec["constrained_streams"] = self._grammar["streams"]
            rec["schemas_served"] = len(self._grammar_names)
            rec["grammar_cache_hits"] = self._grammar["hits"]
            rec["grammar_compiles"] = self._grammar["compiles"]
            rec["grammar_cache_hit_rate"] = round(
                self._grammar["hits"] / self._grammar["streams"], 4)
            rec["grammar_accepts"] = self._grammar["accepts"]
            if self._grammar["tokens"] > 0:
                rec["tokens_masked_frac"] = round(
                    self._grammar["masked_sum"]
                    / self._grammar["tokens"], 4)
        if slo_ttft is not None and ttfts:
            rec["slo_ttft"] = slo_ttft
            rec["slo_ttft_attained"] = round(
                sum(1 for x in ttfts if x <= slo_ttft) / len(ttfts), 4)
        if slo_tpot is not None and tpots:
            rec["slo_tpot"] = slo_tpot
            rec["slo_tpot_attained"] = round(
                sum(1 for x in tpots if x <= slo_tpot) / len(tpots), 4)
        qos_run = any(r.tenant is not None or r.deadline_ms is not None
                      or r.shed for r in self._req.values())
        if qos_run:
            rec.update(self._qos_block(done, makespan, tenant_weights))
        return rec

    def _qos_block(self, done: List[dict], makespan: float,
                   tenant_weights: Optional[Dict[str, float]]) -> dict:
        arrived = len(self._req)
        shed = sum(1 for r in self._req.values() if r.shed)
        qb: dict = {
            "arrived": arrived,
            "shed": shed,
            "shed_rate": round(shed / arrived, 4) if arrived else 0.0,
        }
        with_dl = [d for d in done if d["deadline_ms"] is not None]
        if with_dl:
            dl_hits = sum(1 for d in with_dl if d["deadline_met"])
            qb["deadline_requests"] = len(with_dl)
            qb["deadline_hits"] = dl_hits
            qb["slo_deadline_attained"] = round(
                dl_hits / len(with_dl), 4)
        good = goodput_tokens(done)
        qb["goodput_tokens"] = good
        qb["goodput_tokens_per_sec"] = round(good / makespan, 4) \
            if makespan > 0 else None
        qb["degraded"] = sum(1 for d in done
                             if d["degraded_from"] is not None)
        qb["timeout_evicted"] = sum(
            1 for d in done if d["finish_reason"] == "timeout")
        tenants = sorted({r.tenant for r in self._req.values()
                          if r.tenant is not None})
        if tenants:
            w = tenant_weights or {}
            per: dict = {}
            xs = []
            for t in tenants:
                rids = [rid for rid, r in self._req.items()
                        if r.tenant == t]
                views = [self.request(rid) for rid in rids]
                gtok = goodput_tokens(views)
                n_shed = sum(1 for v in views if v["shed"])
                n_dl = [v for v in views
                        if v["deadline_ms"] is not None
                        and v["finish"] is not None]
                per[t] = {
                    "arrived": len(views),
                    "shed": n_shed,
                    "completed": sum(1 for v in views
                                     if v["finish"] is not None),
                    "goodput_tokens": gtok,
                }
                if n_dl:
                    per[t]["slo_deadline_attained"] = round(
                        sum(1 for v in n_dl if v["deadline_met"])
                        / len(n_dl), 4)
                if self._tenant_costs is not None \
                        and t in self._tenant_costs:
                    c = self._tenant_costs[t]
                    per[t]["cost_units"] = c.get("cost_units", 0.0)
                    per[t]["page_turns"] = c.get("page_turns", 0.0)
                xs.append(gtok / float(w.get(t, 1.0)))
            qb["tenants"] = per
            # Jain index over weight-normalized per-tenant goodput:
            # 1.0 = perfectly weighted-fair, 1/n = one tenant took all
            qb["fairness_jain"] = jain_fairness(xs)
        return qb

    def publish(self, registry=None, prefix: str = "serving_run",
                **slo) -> dict:
        """Derived view into the obs metrics registry: the aggregate
        ``report()`` (which itself stays byte-identical to PR 2/PR 3 —
        the registry is fed FROM it, never the other way) lands as
        ``<prefix>_*`` gauges, one per scalar field, so a Prometheus
        scrape or JSONL snapshot sees the last run's TTFT/TPOT/goodput
        next to the engine's live counters. Returns the record it
        published."""
        from ..obs import metrics as _obs
        reg = registry if registry is not None else _obs.REGISTRY
        rec = self.report(**slo)
        for k, v in rec.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue  # nested tenant dicts / None stay trace-only
            reg.gauge(f"{prefix}_{k}").set(float(v))
        # decode-stall histogram (milliseconds, 1 clock unit = 1000
        # ms — the Request.deadline_ms convention): one observation
        # per finished request whose stream actually stalled. Created
        # ONLY when a nonzero stall exists, so a run whose streams
        # never hiccuped (and every pre-disagg replay of one) leaves
        # the registry byte-identical (PR-5 convention).
        stalls = [v * 1000.0 for v in
                  (self.request(rid)["decode_stall"]
                   for rid in self._req
                   if self._req[rid].finish is not None)
                  if v is not None and v > 0]
        if stalls:
            h = reg.histogram(
                f"{prefix}_decode_stall_ms",
                "worst per-request inter-token gap beyond the "
                "stream's own steady rate",
                buckets=(10.0, 50.0, 100.0, 500.0, 1000.0, 2500.0,
                         5000.0, 10000.0, 25000.0, 100000.0))
            for s in stalls:
                h.observe(s)
        # resident-adapter gauge: ONLY when the run served adapters
        # (the engine streamed the census through on_adapter_resident)
        # — single-model replays leave the registry byte-identical
        if self._adapter_resident is not None:
            reg.gauge("serving_adapter_resident",
                      "LoRA adapters resident in the device bank "
                      "(pinned + retained)").set(
                float(self._adapter_resident))
        # constrained-decoding gauges: ONLY when a constrained row
        # actually ran — grammar=None replays leave the registry
        # byte-identical (PR-5 convention)
        if self._grammar["streams"] > 0:
            reg.gauge("serving_constrained_streams",
                      "requests decoded under a grammar mask").set(
                float(self._grammar["streams"]))
            reg.gauge("serving_grammar_cache_hit_rate",
                      "fraction of constrained admissions whose "
                      "automaton was already resident").set(
                round(self._grammar["hits"]
                      / self._grammar["streams"], 4))
            if self._grammar["tokens"] > 0:
                reg.gauge("serving_tokens_masked_frac",
                          "mean fraction of the vocabulary the "
                          "grammar mask forbade per constrained "
                          "token").set(
                    round(self._grammar["masked_sum"]
                          / self._grammar["tokens"], 4))
        # per-device KV-pool residency: ONLY when the run was sharded
        # (the engine streamed it through on_pool_bytes) — unsharded
        # replays leave the registry byte-identical (PR-5 convention)
        if self._pool_dev_bytes is not None:
            reg.gauge("serving_pool_bytes_per_device",
                      "KV pool bytes resident on one device of the "
                      "TP mesh").set(float(self._pool_dev_bytes))
        return rec

    def to_record(self, policy: str, **extra) -> dict:
        """The canonical ``serving_workload`` row
        (tools/serving_workload_bench.py emits one per policy;
        tools/bench_gate.py serving mode gates routed vs best fixed)."""
        rec = {"bench": "serving_workload", "policy": policy}
        rec.update(self.report(**{k: extra.pop(k) for k in
                                  ("slo_ttft", "slo_tpot",
                                   "tenant_weights")
                                  if k in extra}))
        rec.update(extra)
        return rec
