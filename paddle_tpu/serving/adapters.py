"""Multi-model LoRA serving: host-resident adapter sets + a budgeted
host<->device adapter cache.

Millions of users means thousands of fine-tuned variants of ONE base
model, not thousands of models. The enabling invariant is PR 1's
weights-as-jit-args: the compiled fixed-shape ``decode_n`` program
takes weights as *inputs*, so N LoRA delta sets can ride through one
compiled program as one more input — a device-resident **adapter
bank** of stacked low-rank ``A @ B`` deltas plus a per-row slot-index
vector (data, not shape), the S-LoRA / Punica batched-multi-adapter
design. Admission/eviction of adapters never recompiles anything.

Two pieces, mirroring the paged KV pool's split between device arrays
and host bookkeeping:

- ``AdapterStore`` — the host-resident registry of named delta sets
  (opaque to this module: the serving factory's ``upload_adapter``
  hook is what consumes a delta set, so the real llama factory stores
  stacked ``(L, in, r)/(L, r, out)`` numpy trees while ``serving.sim``
  stores a salt int).
- ``AdapterCache`` — the budgeted device residency manager: a fixed
  number of bank SLOTS (slot 0 is the reserved identity — all-zero
  deltas — so ``adapter=None`` rows ride the same program), an LRU of
  unpinned-but-retained adapters (a finished request's adapter stays
  resident for the next sharer, exactly the PR-5 prefix-page
  retention discipline), **pin-while-in-flight** refcounts (an
  adapter serving a live row can never be evicted under pressure),
  and a ``cache_stats()`` census mirroring ``PagedKVCache``'s:
  ``resident + evictable + free == n_slots - 1`` at all times.

``MemoryError`` on acquire means every non-free slot is pinned — the
engine requeues the request and retries as rows finish, the same
discipline a page-pool refusal gets.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..obs import ledger as obs_ledger


class AdapterStore:
    """Host-resident registry of named LoRA delta sets. Values are
    opaque here — the serving factory's ``upload_adapter`` hook
    interprets them (stacked numpy A/B trees for the real llama
    factory, a salt int for ``serving.sim``). One store may back many
    engines/replicas: it is read-only at serve time."""

    def __init__(self, adapters: Optional[Dict[str, object]] = None):
        self._a: Dict[str, object] = {}
        for name, deltas in (adapters or {}).items():
            self.add(name, deltas)

    def add(self, name: str, deltas) -> None:
        if not isinstance(name, str) or not name:
            raise ValueError("adapter name must be a non-empty string")
        if name in self._a:
            raise ValueError(f"adapter {name!r} already registered")
        self._a[name] = deltas

    def get(self, name: str):
        if name not in self._a:
            raise KeyError(f"unknown adapter {name!r} (registered: "
                           f"{sorted(self._a)})")
        return self._a[name]

    def __contains__(self, name) -> bool:
        return name in self._a

    def __len__(self) -> int:
        return len(self._a)

    def names(self) -> List[str]:
        return sorted(self._a)


class AdapterCache:
    """Device residency manager for one engine's adapter bank.

    ``n_slots`` counts the bank's rows INCLUDING slot 0, the reserved
    identity (all-zero deltas; ``adapter=None`` rows decode through it
    and their math is exactly the base model's — adding an exact float
    zero). Usable slots are ``1 .. n_slots-1``; each holds at most one
    uploaded adapter at a time.

    Lifecycle of a slot, mirroring a ``PagedKVCache`` page:

    - **free**: never uploaded, or reclaimed by an eviction;
    - **resident** (pinned): >= 1 in-flight request decodes with it —
      ``acquire(name, rid)`` pins, ``release(name, rid)`` unpins;
      a pinned adapter is NEVER evicted (pin-while-in-flight);
    - **evictable**: uploaded, zero pins — RETAINED with its content
      live (a later ``acquire`` revives it for free: hit, no upload),
      reclaimed LRU-first only when a miss needs a slot and the free
      list is dry.

    ``acquire`` returns ``(slot, uploaded)``; ``uploaded`` is True
    when a real host->device upload ran (the engine prices it on the
    virtual clock — hits are free). ``MemoryError`` when every
    non-free slot is pinned: nothing can be evicted, the caller
    requeues and retries once a row finishes.

    ``init_bank() -> bank`` and ``upload(bank, slot, deltas) -> bank``
    are the factory's device hooks (functional: the returned bank
    rebinds ``self.bank``, jnp ``.at[slot].set`` style for the real
    factory, in-place numpy for the sim).
    """

    def __init__(self, store: AdapterStore, n_slots: int,
                 init_bank: Callable[[], object],
                 upload: Callable[[object, int, object], object]):
        if n_slots < 2:
            raise ValueError("need n_slots >= 2 (slot 0 is the "
                             "reserved identity; at least one usable "
                             "slot)")
        self.store = store
        self.n_slots = int(n_slots)
        self.bank = init_bank()
        self._upload = upload
        self._slot: Dict[str, int] = {}      # name -> slot (on device)
        self._pins: Dict[str, set] = {}      # name -> holder rids
        self._evictable: Dict[str, bool] = {}  # insertion order = LRU
        self._free = list(range(self.n_slots - 1, 0, -1))
        self._stats = {"hits": 0, "misses": 0, "uploads": 0,
                       "evictions": 0, "refusals": 0}
        # rids whose admission ROLLED BACK after this cache uploaded
        # for them (page-pool refusal): the retry's acquire is a
        # lookup-level hit, but the ADMISSION still paid one upload —
        # note_rollback/took_upload let the engine report per-request
        # hit/upload telemetry that sums to one event per admission
        self._pending_upload: set = set()

    # --- probes (non-acquiring) -------------------------------------------
    def resident(self, name: str) -> bool:
        """Is ``name`` on device right now (pinned or retained)? The
        cluster's adapter-aware placement probe — no pin, no LRU or
        stats mutation, safe to call per placement decision."""
        return name in self._slot

    def slot_of(self, name: str) -> Optional[int]:
        return self._slot.get(name)

    # --- the acquire/release lifecycle ------------------------------------
    def acquire(self, name: str, rid: str, timed=None):
        """Pin ``name`` for in-flight request ``rid``; returns
        ``(slot, uploaded)``. A resident adapter (pinned by others or
        parked evictable) is a HIT — revived, pinned, no upload. A
        miss takes a free slot (or evicts the LRU unpinned adapter)
        and uploads through the factory hook. ``MemoryError`` when
        every non-free slot is pinned — nothing but the refusal
        counter mutates, so the caller can requeue safely.

        ``timed`` (optional ``f -> f()`` wrapper): the upload call
        runs INSIDE it, so a measured engine clock charges the real
        device transfer to the ``adapter_upload`` span instead of
        letting it bleed into the next prefill/decode call (a fixed
        clock charges its per-upload cost either way)."""
        self.store.get(name)  # unknown adapters refuse loudly
        pins = self._pins.setdefault(name, set())
        if rid in pins:
            raise ValueError(f"adapter {name!r} already pinned for "
                             f"{rid!r}")
        if name in self._slot:
            self._evictable.pop(name, None)  # revival: LRU -> resident
            pins.add(rid)
            self._stats["hits"] += 1
            return self._slot[name], False
        if not self._free and not self._evictable:
            if not pins:
                self._pins.pop(name, None)  # undo the setdefault
            self._stats["refusals"] += 1
            raise MemoryError(
                f"adapter cache exhausted: {self.n_slots - 1} slots "
                f"all pinned by in-flight rows — requeue {rid!r} and "
                "retry when a row finishes")
        self._stats["misses"] += 1
        victim = None
        if self._free:
            slot = self._free.pop()
        else:
            # LRU eviction: the least-recently-parked unpinned adapter
            victim = next(iter(self._evictable))
            del self._evictable[victim]
            slot = self._slot.pop(victim)
            self._pins.pop(victim, None)

        def _run():
            return self._upload(self.bank, slot, self.store.get(name))
        try:
            self.bank = timed(_run) if timed is not None else _run()
        except Exception:
            # exception-safe: a raising upload hook (e.g. a delta set
            # whose rank mismatches the factory's LoRAConfig, caught
            # by the hook's shape check BEFORE any write — the bank
            # rebinds only on success) must not leak the slot out of
            # the census: restore the bookkeeping exactly (an evicted
            # victim's content was never overwritten) and stay loud
            if victim is None:
                self._free.append(slot)
            else:
                self._slot[victim] = slot
                self._evictable[victim] = True
            self._stats["misses"] -= 1
            if not pins:
                self._pins.pop(name, None)
            raise
        if victim is not None:
            self._stats["evictions"] += 1
        self._stats["uploads"] += 1
        self._slot[name] = slot
        pins.add(rid)
        return slot, True

    def release(self, name: str, rid: str) -> None:
        """Unpin ``rid``'s hold on ``name``. The last unpin RETAINS
        the adapter (slot parked in the evictable LRU, content live)
        instead of freeing it — the next sharer hits."""
        pins = self._pins.get(name)
        if pins is None or rid not in pins:
            raise ValueError(f"release: {name!r} holds no pin for "
                             f"{rid!r}")
        pins.discard(rid)
        if not pins:
            self._pins.pop(name, None)
            if name in self._slot:
                self._evictable[name] = True

    def note_rollback(self, name: str, rid: str,
                      uploaded: bool) -> None:
        """``rid``'s admission failed AFTER ``acquire`` (page-pool
        refusal): release the pin and — when that acquire uploaded —
        remember the rid, so ``took_upload`` can attribute the upload
        to the admission that eventually succeeds instead of
        reporting the retry's lookup-hit as a free ride."""
        self.release(name, rid)
        if uploaded:
            self._pending_upload.add(rid)

    def forget_pending(self, rid: str) -> None:
        """Drop ``rid``'s pending-upload marker (no-op without one):
        the request left this engine — shed, or requeued to another
        replica — without re-admitting, so nothing will ever consume
        the marker and a recycled rid must not inherit it."""
        self._pending_upload.discard(rid)

    def took_upload(self, rid: str, uploaded: bool) -> bool:
        """Did ``rid``'s ADMISSION pay an upload — either on this
        acquire or on an earlier rolled-back one? Consumes the
        pending-upload marker."""
        if rid in self._pending_upload:
            self._pending_upload.discard(rid)
            return True
        return uploaded

    # --- census ------------------------------------------------------------
    def resident_count(self) -> int:
        """Adapters on device right now (pinned + retained) — the
        ``serving_adapter_resident`` gauge's value."""
        return len(self._slot)

    def populations(self) -> Tuple[int, int, int]:
        """The census populations (pinned, evictable, free) — the
        counts ``census_ok`` balances against capacity and the cost
        ledger's occupancy sampler integrates per turn."""
        pinned = sum(1 for n in self._slot if self._pins.get(n))
        return pinned, len(self._evictable), len(self._free)

    def pin_owners(self) -> Dict[str, List[str]]:
        """adapter name -> sorted holder rids, pinned slots only —
        the attribution view the cost ledger splits slot-turns by."""
        return {n: sorted(self._pins[n]) for n in self._slot
                if self._pins.get(n)}

    def census_ok(self) -> bool:
        """The accounting invariant, one line: every usable slot
        (slot 0 is the reserved identity) is exactly one of
        pinned-resident / evictable / free (arithmetic shared with
        every budgeted pool via ``obs.ledger.census_balanced``)."""
        return obs_ledger.census_balanced(self.n_slots - 1,
                                          *self.populations())

    def cache_stats(self) -> dict:
        """Adapter-cache accounting, the ``PagedKVCache.cache_stats``
        shape: the live slot census (``resident_slots`` = pinned,
        ``evictable_slots`` = retained at zero pins, ``free_slots``;
        the three sum to ``n_slots - 1``) plus cumulative
        hit/miss/upload/eviction/refusal counters and the derived
        hit rate over lookups."""
        pinned = sum(1 for n in self._slot if self._pins.get(n))
        hits, misses = self._stats["hits"], self._stats["misses"]
        lookups = hits + misses
        return {
            "n_slots": self.n_slots - 1,
            "resident_slots": pinned,
            "evictable_slots": len(self._evictable),
            "free_slots": len(self._free),
            "resident_adapters": len(self._slot),
            "hits": hits,
            "misses": misses,
            "hit_rate": round(hits / lookups, 4) if lookups else 0.0,
            "uploads": self._stats["uploads"],
            "evictions": self._stats["evictions"],
            "refusals": self._stats["refusals"],
        }
