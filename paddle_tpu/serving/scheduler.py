"""QoS scheduling for the serving engine: the front door between trace
arrival and admission.

PR-2's engine admits FIFO — every request equal, no deadline consulted,
and under overload the queue just grows. This module is the scheduling
layer production stacks win with (Orca: iteration-level scheduling is
where continuous batching pays off; Clockwork: SLO attainment comes
from admission-time deadline-feasibility checks over a predictable
cost model):

- **Strict priority classes** above **weighted fair queueing across
  tenants** (start-time fair queueing: each tenant carries a virtual
  finish tag advanced by served-work/weight; the lowest tag in the top
  priority class goes next, so an aggressive tenant can saturate only
  its weight share, not the queue).
- **Deadline-feasibility admission**: estimated completion =
  now + queued-prefill delay + prefill + ceil(budget/chunk) x decode x
  headroom, from the engine's observed (EWMA) or fixed-clock per-action
  costs. A request that cannot meet its ``deadline_ms`` is shed AT
  ADMISSION — before burning prefill compute — not timed out after.
- **Overload as policy**: bounded queues shed lowest-value first
  (lowest priority class, then the request least likely to make its
  deadline, then latest arrival), and graceful-degradation tiers clamp
  ``max_new_tokens`` (1.0 -> 0.75 -> 0.5 -> 0.25 of budget) before
  rejecting outright — a shorter answer in time beats a full answer
  late or none at all.
- **Aging** (optional): a waiting request's effective priority rises by
  one class per ``aging`` clock units, so strict priority cannot
  starve a low class under a saturating high-priority tenant.

The scheduler owns the waiting set; the engine asks ``select`` for the
next admission wave, records the sheds, and ``commit``s the requests it
actually admitted (a wave blocked on slots/pages stays queued and is
NOT charged to its tenant's fair-queue tag). Timeout of RUNNING
requests is the engine's half of the contract, unified with the
``cancel_after`` eviction path.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Tuple

from .workload import Request


class ServiceEstimator:
    """Per-action cost model the feasibility check prices against.

    Seeded from the engine clock's fixed costs (exact under
    ``clock="fixed"``); under a measured clock the engine feeds every
    observed prefill/decode duration back and the EWMA tracks the real
    machine. ``headroom`` multiplies the decode estimate — co-scheduled
    prefills steal turns from a row's decode stream, so the lone-row
    lower bound is optimistic by design.

    ``prefill_unit`` + ``chunk_tokens`` switch prefill pricing from
    flat-per-call to per-chunk: ``prefill_cost(n_uncached_tokens)``
    then scales with the chunks the engine will actually compute —
    the cache-aware admission price (a request whose prompt is mostly
    prefix-cache resident is CHEAP, and the feasibility check should
    know that before shedding it).
    """

    def __init__(self, prefill: float = 1.0, decode: float = 1.0,
                 alpha: float = 0.25,
                 prefill_unit: Optional[float] = None,
                 chunk_tokens: Optional[int] = None):
        if prefill <= 0 or decode <= 0:
            raise ValueError("estimator costs must be positive")
        self.costs = {"prefill": float(prefill), "decode": float(decode)}
        if prefill_unit is not None:
            if prefill_unit <= 0:
                raise ValueError("estimator costs must be positive")
            if not chunk_tokens or chunk_tokens <= 0:
                raise ValueError("prefill_unit pricing needs "
                                 "chunk_tokens (the prefill chunk "
                                 "size in tokens)")
            self.costs["prefill_unit"] = float(prefill_unit)
        self.chunk_tokens = int(chunk_tokens) if chunk_tokens else None
        self.alpha = alpha

    def prefill_cost(self, uncached_tokens: Optional[int] = None,
                     prompt_tokens: Optional[int] = None) -> float:
        """Admission price of one prefill. Per-chunk pricing with a
        known uncached length charges exactly the chunks the engine
        will compute: ``ceil(prompt/chunk) - cached//chunk``, floored
        at one — the engine resumes at the CHUNK-ALIGNED cached count
        and the FINAL chunk always runs (the last-position logits
        must exist), so a cached prefix that is not chunk-aligned
        still pays for its partial chunk. Without ``prompt_tokens``
        the uncached length alone approximates (exact when the cache
        is page==chunk aligned); without per-chunk pricing, or
        without a probe result, the flat per-call cost keeps the
        legacy arithmetic exactly."""
        unit = self.costs.get("prefill_unit")
        if unit is None or uncached_tokens is None \
                or self.chunk_tokens is None:
            return self.prefill
        c = self.chunk_tokens
        u = max(0, uncached_tokens)
        if prompt_tokens is None:
            return unit * max(1, math.ceil(u / c))
        total = max(1, math.ceil(prompt_tokens / c))
        return unit * max(1, total - (prompt_tokens - u) // c)

    def observe(self, kind: str, dt: float):
        if dt <= 0:
            return
        c = self.costs.get(kind)
        self.costs[kind] = dt if c is None else \
            (1 - self.alpha) * c + self.alpha * dt

    @property
    def prefill(self) -> float:
        return self.costs["prefill"]

    @property
    def decode(self) -> float:
        return self.costs["decode"]


@dataclasses.dataclass
class SchedDecision:
    """One scheduler turn: the wave to admit (budgets possibly clamped
    by a degradation tier) and the requests shed this turn (original
    request, reason)."""

    wave: List[Request]
    shed: List[Tuple[Request, str]]
    degraded: Dict[str, Tuple[int, int]]  # rid -> (new, orig) budgets


class _Entry:
    __slots__ = ("req", "enq_t")

    def __init__(self, req: Request, enq_t: float):
        self.req = req
        self.enq_t = enq_t


class QoSScheduler:
    """SLO-aware admission + per-tenant fairness + overload shedding.

    ``tenant_weights``: WFQ weight per tenant (default 1.0; requests
    without a tenant pool under ``default_tenant``). ``max_queue``
    bounds the waiting set (None = unbounded; shedding then comes only
    from deadline infeasibility). ``degrade_tiers`` are budget
    fractions tried in order before shedding an infeasible-at-full-
    budget request; () disables degradation. ``headroom`` scales the
    decode-time estimate in the feasibility check. ``aging`` promotes a
    waiting request one priority class per that many clock units
    (None = strict classes, starvation possible by design).
    """

    name = "qos"

    def __init__(self, *, tenant_weights: Optional[Dict[str, float]]
                 = None, default_tenant: str = "_default",
                 max_queue: Optional[int] = None,
                 degrade_tiers: Tuple[float, ...] = (1.0, 0.75, 0.5,
                                                     0.25),
                 headroom: float = 1.5,
                 aging: Optional[float] = None,
                 incident_degrade: Optional[float] = None):
        self.tenant_weights = dict(tenant_weights or {})
        for t, w in self.tenant_weights.items():
            if w <= 0:
                raise ValueError(f"tenant {t!r}: weight must be > 0")
        self.default_tenant = default_tenant
        if max_queue is not None and max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None)")
        self.max_queue = max_queue
        if any(not 0 < f <= 1 for f in degrade_tiers):
            raise ValueError("degrade_tiers must be fractions in (0, 1]")
        self.degrade_tiers = tuple(sorted(degrade_tiers, reverse=True))
        if headroom < 1.0:
            raise ValueError("headroom must be >= 1.0")
        self.headroom = headroom
        if aging is not None and aging <= 0:
            raise ValueError("aging must be > 0 clock units (or None)")
        self.aging = aging
        if incident_degrade is not None \
                and not 0.0 < incident_degrade <= 1.0:
            raise ValueError("incident_degrade is a budget fraction "
                             "in (0, 1] (or None to disable incident-"
                             "driven degradation)")
        # incident-driven TIER ACTUATION (the autoscaling control
        # plane's "degrade before shedding" action): while any
        # page-severity incident delivered through note_incident is
        # still OPEN, admission budgets are clamped to at most this
        # fraction — every candidate degrades to a shorter answer
        # before the feasibility check ever sheds it. None (the
        # default) keeps the pre-actuation arithmetic bit-for-bit.
        self.incident_degrade = incident_degrade
        # the SLO subscription seam (obs.slo.SLOMonitor on_incident /
        # subscribe): every incident delivered accumulates here; page
        # incidents additionally arm the incident_degrade clamp while
        # open. Survives reset(): incident history is operator state,
        # not per-run queue state.
        self.incidents_seen: List = []
        self._page_open: List = []
        # overload tracking for the SPECULATIVE route's fallback
        # (``ServingEngine(spec=...)`` arms it; untracked otherwise —
        # the PR-11 "tracked only when a consumer is armed"
        # discipline): while any page-severity incident delivered
        # through note_incident stays open, ``overload_active()``
        # answers True and the engine decodes spec rows plain —
        # draft compute is waste exactly when capacity is scarce.
        self.track_overload = False
        self._overload_open: List = []
        # pool-byte pressure tracking for the QUANTIZED page tier
        # (``ServingEngine(kv_quant='pressure')`` arms it; same
        # tracked-only-when-armed discipline): while any incident
        # whose evidence names the ``pool_bytes_per_device`` signal
        # stays open, ``pressure_active()`` answers True and the
        # engine compacts parked pages to int8 — compaction before
        # shedding, the gentler rung below the degrade clamp.
        self.track_pressure = False
        self._pressure_open: List = []
        # preemption-as-swap rung for the HOST-ARENA tier
        # (``ServingEngine(hostmem=...)`` arms it; same tracked-only-
        # when-armed discipline): when a wave candidate blocks on
        # capacity, ``preempt_victim`` names the running row worth
        # swapping OUT to the host arena so the blocked request can
        # run — the rung between degrade (shorter answers) and shed
        # (no answer): the victim still finishes, just later.
        self.track_preempt = False
        # grammar floor for CONSTRAINED streams
        # (``ServingEngine(grammar=...)`` arms it; same armed-only-
        # when-a-consumer-exists discipline): a callable mapping a
        # Request to the automaton's shortest-accept length (None for
        # free rows). A degrade tier must never clamp a constrained
        # budget below the shortest string its grammar accepts —
        # that would GUARANTEE a structurally broken answer, strictly
        # worse than shedding.
        self.grammar_min_tokens = None
        self.reset()

    # --- state ------------------------------------------------------------
    def reset(self):
        """Fresh run: empty queue, fair-queue tags back to zero (an
        engine reuses one scheduler across ``run`` calls). The
        overload-tracking list clears too: a run's per-run SLO
        monitor is discarded at run end, so an incident still open
        then would otherwise NEVER close and park the next run's
        spec route forever (``incidents_seen``/the degrade clamp
        keep their PR-11 survive-reset semantics — they are operator
        state)."""
        self._q: Dict[str, _Entry] = {}
        self._tags: Dict[str, float] = {}
        self._priced: Dict[str, float] = {}
        self._overload_open = []
        self._pressure_open = []

    def note_incident(self, incident):
        """``obs.slo`` incident callback: record that an SLO incident
        fired (e.g. ``SLOMonitor(..., on_incident=[sched.
        note_incident])``). With ``incident_degrade`` unset this is
        detect-and-report only — admission arithmetic untouched.
        With it set, a delivered PAGE-severity incident arms the
        degradation clamp for as long as the incident object stays
        open (incidents close in place, so no un-note call exists or
        is needed): the tier actuation the autoscaling control plane
        drives through this seam."""
        self.incidents_seen.append(incident)
        if getattr(incident, "severity", None) == "page":
            if self.incident_degrade is not None:
                self._page_open.append(incident)
            if self.track_overload:
                self._overload_open.append(incident)
        if self.track_pressure and isinstance(
                getattr(incident, "evidence", None), dict) \
                and incident.evidence.get("signal") \
                == "pool_bytes_per_device":
            # any severity qualifies: compaction is the low-regret
            # rung, worth taking on a warn-level byte breach before
            # anything pages
            self._pressure_open.append(incident)

    def pressure_active(self) -> bool:
        """True while any pool-byte-pressure incident delivered
        through ``note_incident`` is still open (armed via
        ``track_pressure``; always False untracked). The quantized
        page tier's trigger: closed incidents prune lazily, so
        compaction stops the moment stored bytes recover below the
        rule's bound."""
        if self._pressure_open:
            self._pressure_open = [i for i in self._pressure_open
                                   if getattr(i, "open", False)]
        return bool(self._pressure_open)

    def overload_active(self) -> bool:
        """True while any page-severity incident delivered through
        ``note_incident`` is still open (armed via
        ``track_overload``; always False untracked). The speculative
        route's fallback signal: incidents close in place, so closed
        ones are pruned lazily and the route re-enables the moment
        the last one resolves."""
        if self._overload_open:
            self._overload_open = [i for i in self._overload_open
                                   if getattr(i, "open", False)]
        return bool(self._overload_open)

    def _degrade_cap(self) -> Optional[float]:
        """The active incident-degradation budget fraction, or None.
        Closed incidents are pruned lazily — the clamp lifts the
        moment the last armed incident closes."""
        if self.incident_degrade is None:
            return None
        if self._page_open:
            self._page_open = [i for i in self._page_open
                               if getattr(i, "open", False)]
        return self.incident_degrade if self._page_open else None

    def waiting(self) -> int:
        return len(self._q)

    def oldest_arrival(self) -> float:
        return min(e.req.arrival for e in self._q.values())

    def queued_rids(self) -> List[str]:
        return list(self._q)

    def queued_requests(self) -> List[Request]:
        """Non-destructive view of the queued requests in (arrival,
        rid) order — the disaggregated placement policy's backlog
        probe (``drain_queue`` is the destructive twin)."""
        return sorted((e.req for e in self._q.values()),
                      key=lambda r: (r.arrival, r.rid))

    def _tenant(self, r: Request) -> str:
        return r.tenant if r.tenant is not None else self.default_tenant

    def _weight(self, tenant: str) -> float:
        return float(self.tenant_weights.get(tenant, 1.0))

    def _eff_priority(self, e: _Entry, now: float) -> int:
        p = e.req.priority
        if self.aging is not None:
            p += int((now - e.req.arrival) / self.aging)
        return p

    # --- enqueue + queue-bound shedding -----------------------------------
    def enqueue(self, r: Request, now: float) \
            -> List[Tuple[Request, str]]:
        """Accept an arrival; under a full queue, shed the lowest-value
        request (possibly the newcomer). Returns this turn's sheds."""
        t = self._tenant(r)
        if not any(self._tenant(e.req) == t for e in self._q.values()):
            # SFQ re-activation: a tenant returning from idle re-enters
            # at the current virtual time (the min tag among tenants
            # with queued work), never below — idle time earns no
            # credit, but accumulated debt is kept
            live = [self._tags.get(self._tenant(e.req), 0.0)
                    for e in self._q.values()]
            if live:
                self._tags[t] = max(self._tags.get(t, 0.0), min(live))
        self._q[r.rid] = _Entry(r, now)
        if self.max_queue is None or len(self._q) <= self.max_queue:
            return []
        victim = min(self._q.values(),
                     key=lambda e: self._shed_key(e, now))
        del self._q[victim.req.rid]
        return [(victim.req, f"queue bound ({self.max_queue}) — lowest-"
                 "value victim (priority, deadline slack, recency)")]

    def _shed_key(self, e: _Entry, now: float):
        """Lowest value first: lowest effective priority; then the
        request LEAST likely to meet its deadline (smallest slack —
        shedding the doomed wastes the least); deadline-free requests
        rank above any deadline (infinite slack); latest arrival last."""
        r = e.req
        dl = r.deadline_time()
        slack = math.inf if dl is None else dl - now
        return (self._eff_priority(e, now), slack, -r.arrival, r.rid)

    # --- the admission turn ------------------------------------------------
    def select(self, now: float, *, max_batch: int,
               est: ServiceEstimator, decode_chunk: int = 1,
               match_prefix=None,
               backlog_cost: float = 0.0) -> SchedDecision:
        """Build the next admission wave.

        Order: strict effective priority, then WFQ across tenants
        (lowest virtual finish tag), then FIFO within a tenant. Each
        candidate passes the deadline-feasibility check at its wave
        position (earlier wave members' prefills delay it); an
        infeasible candidate tries the degradation tiers, then is shed.
        Tags are NOT charged here — the engine ``commit``s what it
        actually admitted.

        ``match_prefix`` (optional, ``PagedKVCache.match_prefix``-
        shaped: tokens -> cached token count) makes admission CACHE-
        AWARE: each candidate's prefill is priced at
        ``est.prefill_cost(len(prompt) - match_prefix(prompt))``, so a
        recurring system prompt both admits more easily and delays the
        rest of the wave less. ``None`` keeps the flat legacy pricing
        bit-for-bit.

        ``backlog_cost`` seeds the queued-prefill delay with work
        ALREADY committed ahead of this wave — the async prefill
        lane's remaining chunks (``ServingEngine._lane_backlog_cost``)
        — so feasibility verdicts stay honest when admission and
        prefill are decoupled. 0.0 (the default) keeps the legacy
        arithmetic exactly.
        """
        shed: List[Tuple[Request, str]] = []
        degraded: Dict[str, Tuple[int, int]] = {}
        wave: List[Request] = []
        cap = self._degrade_cap()  # once per turn: one incident state
        # governs the whole wave
        remaining = dict(self._q)
        # prefill units ahead of the next candidate (the lane's
        # committed chunks first, then this wave's admitted prefills)
        queued_cost = float(backlog_cost)
        while remaining and len(wave) < max_batch:
            top = max(self._eff_priority(e, now)
                      for e in remaining.values())
            cands = [e for e in remaining.values()
                     if self._eff_priority(e, now) == top]
            tenants = {self._tenant(e.req) for e in cands}
            tenant = min(tenants,
                         key=lambda t: (self._tags.get(t, 0.0), t))
            e = min((c for c in cands if self._tenant(c.req) == tenant),
                    key=lambda c: (c.req.arrival, c.req.rid))
            del remaining[e.req.rid]
            uncached = None
            if match_prefix is not None:
                uncached = max(0, len(e.req.prompt)
                               - int(match_prefix(e.req.prompt)))
            elif "prefill_unit" in est.costs:
                # per-chunk clock pricing with NO probe (the cache-off
                # arm): everything computes — price the full prompt,
                # not the flat per-call cost, or every candidate looks
                # one-chunk cheap and blows its admitted deadline
                uncached = len(e.req.prompt)
            r, verdict, cost = self._feasible(e.req, now, queued_cost,
                                              est, decode_chunk,
                                              uncached, cap=cap)
            if r is None:
                del self._q[e.req.rid]
                shed.append((e.req, verdict))
                continue
            queued_cost += cost  # only ADMITTED prefills delay later
            # wave members (a shed candidate never runs)
            # bank the full admission price (prefill + budgeted decode
            # with headroom) — the cost ledger's calibration signal:
            # estimator-priced vs ledger-actual units per admission
            self._priced[r.rid] = (
                cost + math.ceil(r.max_new_tokens / decode_chunk)
                * est.decode * self.headroom)
            if r.max_new_tokens < e.req.max_new_tokens:
                degraded[r.rid] = (r.max_new_tokens,
                                   e.req.max_new_tokens)
            wave.append(r)
        return SchedDecision(wave=wave, shed=shed, degraded=degraded)

    def _feasible(self, r: Request, now: float, queued_cost: float,
                  est: ServiceEstimator, decode_chunk: int,
                  uncached: Optional[int] = None,
                  cap: Optional[float] = None):
        """Clockwork-style check: estimated completion =
        now + queued_cost + own prefill        (admissions serialize;
                                                each priced by its
                                                UNCACHED length when a
                                                probe is given)
            + ceil(budget / decode_chunk) * decode * headroom.
        ``cap`` (the open-incident degradation fraction) replaces the
        full-budget top tier: every admission — deadline-free ones
        included — is clamped to at most ``cap`` x its budget while
        an incident is open, trading answer length for admission
        headroom BEFORE any shed. Returns (request-or-degraded-copy,
        rule, prefill_cost) or (None, shed reason, 0.0)."""
        pf = est.prefill_cost(uncached, prompt_tokens=len(r.prompt))
        dl = r.deadline_time()
        budget = r.max_new_tokens
        # degrade floor: a constrained stream is never clamped below
        # its automaton's shortest-accept length (armed by the engine
        # through ``grammar_min_tokens``; free rows and unarmed
        # schedulers keep the legacy floor of 1 bit-for-bit)
        floor = 1
        if self.grammar_min_tokens is not None:
            g = self.grammar_min_tokens(r)
            if g is not None:
                floor = max(1, min(int(g), budget))
        if dl is None:
            if cap is not None:
                b = max(floor, math.ceil(budget * cap))
                if b < budget:
                    return (dataclasses.replace(r, max_new_tokens=b),
                            f"incident degradation tier {cap} "
                            f"({b}/{budget} tokens)", pf)
            return r, "no deadline", pf
        t0 = now + queued_cost + pf
        # the FULL budget is always tried first — degrade_tiers only
        # say what to fall back to when it does not fit (a tier tuple
        # without 1.0 must not silently clamp feasible requests).
        # Under an open incident the cap IS the top tier.
        if cap is not None:
            tiers = (cap,) + tuple(f for f in self.degrade_tiers
                                   if f < cap)
        else:
            tiers = (1.0,) + tuple(f for f in self.degrade_tiers
                                   if f < 1.0)
        for frac in tiers:
            b = max(floor, math.ceil(budget * frac))
            fin = t0 + math.ceil(b / decode_chunk) * est.decode \
                * self.headroom
            if fin <= dl + 1e-9:
                if b >= budget:
                    return r, "feasible at full budget", pf
                return (dataclasses.replace(r, max_new_tokens=b),
                        f"degraded to tier {frac} ({b}/{budget} tokens)",
                        pf)
        return None, (
            f"deadline-infeasible at admission: even the lowest "
            f"degradation tier ({tiers[-1]}) finishes past the "
            f"deadline (deadline in {max(0.0, dl - now):.3f} units, "
            f"estimated service {t0 - now + est.decode:.3f}+)"), 0.0

    def preempt_victim(self, now: float, blocked: Request,
                       running: List[Tuple[str, Request, int]]) \
            -> Optional[str]:
        """Name the running row to swap out so ``blocked`` (a selected
        wave member the engine could not admit for capacity) can run —
        or None when no row is worth displacing. Armed via
        ``track_preempt`` (always None untracked: the engine falls
        through to the legacy stay-queued/shed path bit-for-bit).

        ``running`` is the engine's view of in-flight rows as
        ``(rid, request, emitted_tokens)``. A victim must be STRICTLY
        lower priority than the blocked request's effective (aged)
        priority — equal-priority swapping would thrash — and must
        still have decode budget left (displacing a row about to
        finish buys nothing and pays two transfers). Among eligible
        victims: lowest priority first, then fewest emitted tokens
        (the least sunk decode work re-queued), then rid for
        determinism."""
        if not self.track_preempt:
            return None
        e = self._q.get(blocked.rid)
        want = self._eff_priority(e, now) if e is not None \
            else blocked.priority
        cands = [(rid, r, emitted) for rid, r, emitted in running
                 if r.priority < want
                 and emitted < r.max_new_tokens - 1]
        if not cands:
            return None
        return min(cands,
                   key=lambda c: (c[1].priority, c[2], c[0]))[0]

    def commit(self, rid: str, budget: Optional[int] = None):
        """The engine ADMITTED ``rid``: leave the queue and charge the
        tenant's fair-queue tag by served-work/weight. ``budget`` is
        the budget that actually ran (the degradation-clamped value
        when a tier fired) — a degraded tenant is charged for the
        short answer it got, not the long one it asked for.
        Uncommitted selections stay queued for the next turn,
        uncharged."""
        e = self._q.pop(rid)
        t = self._tenant(e.req)
        b = budget if budget is not None else e.req.max_new_tokens
        cost = (len(e.req.prompt) + b) / self._weight(t)
        self._tags[t] = self._tags.get(t, 0.0) + cost

    def priced(self, rid: str) -> Optional[float]:
        """The admission price ``select`` computed for ``rid`` on its
        LAST selection (prefill + budgeted decode with headroom), or
        None for a request never selected. Read by the engine at
        commit time to seed the cost ledger's estimator-vs-actual
        calibration rows; purely observational — admission arithmetic
        never reads it back."""
        return self._priced.get(rid)

    def drain_queue(self) -> List[Request]:
        """Remove and return EVERY queued (never-admitted) request, in
        (arrival, rid) order — the cluster router's drain AND failover
        path: a draining replica keeps its in-flight rows and hands
        its queue back; a replica declared dead after a crash hands
        back everything that was still queued there (including
        arrivals placed during the undetected-silence window).
        Fair-queue tags are untouched (history of served work survives
        the drain; a corpse's tags die with its session). A RESUMED
        request re-enqueues elsewhere with its original arrival, so
        aging credits the waiting it already suffered and
        ``shed_expired`` still prices its deadline honestly."""
        reqs = sorted((e.req for e in self._q.values()),
                      key=lambda r: (r.arrival, r.rid))
        self._q.clear()
        return reqs

    def shed_expired(self, now: float) -> List[Tuple[Request, str]]:
        """Drop queued requests whose deadline already passed (they
        could only be timed out later for more cost)."""
        out = []
        for rid in list(self._q):
            dl = self._q[rid].req.deadline_time()
            if dl is not None and now > dl + 1e-9:
                e = self._q.pop(rid)
                out.append((e.req, "deadline passed while queued"))
        return out
