"""Continuous-batching serving engine over the dense/paged decode stack.

The layer between a request stream and the compiled decode programs —
what the reference's inference engine wraps around
fused_multi_transformer, rebuilt TPU-native on this repo's backends:

  arrive -> (admission window) -> route -> prefill -> decode slots
         -> complete / evict, pages freed for the next request.

One engine, two execution backends, one policy seam:

- **paged** (continuous batching): per-request chunked prefill into the
  paged KV pool, then ONE fixed-shape jitted decode step for whatever
  mix of requests occupies the slots — tables and lengths are data, so
  admission/eviction never recompiles. Shared prompt prefixes ride the
  pool's refcounted prefix cache (acquire before allocate, register
  after prefill) and skip their cached prefill chunks.
- **dense** (wave batching): a uniform admission wave runs on the dense
  compiled cache as one batch — prefill + per-token decode steps — the
  backend that wins uniform near-full shapes on chip (PERF record 37).
- **policy**: ``RoutedPolicy`` (default) delegates to
  ``route_decode``/``_Serving.pick`` per admission wave and logs WHICH
  rule fired; ``FixedPolicy`` pins one backend (the bench's
  dense-only/paged-only arms). Policies are pluggable objects — a
  custom one needs only ``route(wave, ctx)``.

Admission shares its config surface with ``inference.DynamicBatcher``
(``BatchingConfig``: max_batch + max_delay) — the request/response
batcher and this token-stream batcher coalesce with the same knobs.

Time is VIRTUAL: the clock advances by the measured wall duration of
each jitted call (``clock="measured"``, the bench mode — queueing and
compute show up honestly without sleeping through arrival gaps) or by
fixed per-action costs (``clock="fixed"``, the deterministic test mode:
same trace -> same completion order, timestamps, slot occupancy).
Replay a trace twice with the same engine to exclude compile time: the
first pass warms every program shape.
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..inference import BatchingConfig
from ..jax_compat import named_sharding
from ..obs import ledger as obs_ledger
from ..obs import metrics as obs_metrics
from ..obs import slo as obs_slo
from ..obs import trace as obs_trace
from ..models.nlp.llama_decode import (as_grammar_config,
                                       as_lora_config,
                                       as_spec_config, as_tp_config,
                                       llama_serving_decode_factory,
                                       repage_kv_data, route_decode,
                                       transcode_kv_data,
                                       tree_device_bytes)
from ..ops.pallas.paged_attention import PagedKVCache
from .adapters import AdapterCache, AdapterStore
from .grammar import GrammarCache, GrammarStore, TokenVocab
from .hostmem import HostArena, as_hostmem_config
from .metrics import MetricsCollector
from .scheduler import QoSScheduler, ServiceEstimator
from .workload import Request, iter_jsonl_tolerant


class EngineClock:
    """Virtual time. ``measured``: each timed action adds its wall
    duration (block_until_ready'd). ``fixed``: each action adds
    ``costs[kind]`` (default 1.0) — fully deterministic."""

    def __init__(self, mode: str = "measured", costs: dict | None = None):
        if mode not in ("measured", "fixed"):
            raise ValueError(f"clock {mode!r}: use 'measured' or 'fixed'")
        self.mode = mode
        self.costs = costs or {}
        self.t = 0.0
        # measured mode: cumulative wall seconds spent inside timed
        # actions (the run's device-dispatch time, read by the engine's
        # host-overhead decomposition); fixed mode never touches it
        self.dev_wall = 0.0

    def now(self) -> float:
        return self.t

    def advance_to(self, t: float):
        self.t = max(self.t, t)

    def timed(self, kind: str, fn, units: Optional[int] = None,
              cost: Optional[float] = None):
        """``units`` (work items, e.g. prefill chunks computed) prices
        a fixed-clock action per unit WHEN the cost table carries a
        ``<kind>_unit`` entry — the honest clock for prefix caching,
        where a cache hit skips real work. ``units=0`` (a call that
        computes NOTHING — e.g. a fully-cached prefill) is free on the
        fixed clock even without a per-unit entry: zero work priced at
        the flat per-call cost would charge for compute that never
        ran. ``cost`` (fixed clock only) overrides the table outright
        — the async prefill lane uses it to split a flat per-call
        prefill cost evenly across a prompt's chunk calls, so running
        N bounded calls instead of one monolithic call charges the
        SAME total. A ragged-fused call passes a LIST of per-chunk
        costs (one flat split per row advanced) and is charged their
        SUM — k chunks fused into one program price identically to k
        sequential chunk calls, never re-multiplied or discounted.
        Without units/cost the flat per-call cost keeps legacy replays
        bit-identical; a measured clock always charges wall time."""
        if self.mode == "fixed":
            out = fn()
            if cost is not None:
                self.t += float(sum(cost)) \
                    if isinstance(cost, (list, tuple)) else float(cost)
            elif units is not None and (units == 0
                                        or f"{kind}_unit"
                                        in self.costs):
                self.t += float(self.costs.get(f"{kind}_unit", 0.0)) \
                    * units
            else:
                self.t += float(self.costs.get(kind, 1.0))
            return out
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        self.t += dt
        self.dev_wall += dt
        return out


class _LedgerClock(EngineClock):
    """An ``EngineClock`` that books every priced delta on a
    ``CostLedger``. Attribution is pushed onto the clock by ``_timed``
    immediately before the call (``push_attr``) and consumed by
    exactly one ``timed``; a priced call that reaches the clock with
    no attribution lands in the ledger's ``unattributed`` bucket,
    which the conservation audit requires to be zero. ``advance_to``
    books the idle jump, so per engine
    ``sum(attributed) + idle == elapsed`` exactly — the arithmetic of
    the wrapped clock is untouched (super() does all of it), so a
    ledger-armed replay's outputs stay byte-identical."""

    def __init__(self, mode, costs, ledger, label: str):
        super().__init__(mode, costs)
        self._ledger = ledger
        self.label = label
        self._attr = None

    def push_attr(self, rid=None, rids=None, weights=None):
        self._attr = (rid, rids, weights)

    def timed(self, kind, fn, units=None, cost=None):
        t0 = self.t
        out = super().timed(kind, fn, units, cost)
        attr, self._attr = self._attr, None
        dt = self.t - t0
        if attr is None:
            self._ledger.charge(self.label, kind, dt)
        else:
            rid, rids, weights = attr
            if rids:
                self._ledger.charge(self.label, kind, dt, rids=rids,
                                    weights=weights)
            else:
                self._ledger.charge(self.label, kind, dt,
                                    rid=rid if rid is not None
                                    else "engine")
        return out

    def advance_to(self, t):
        t0 = self.t
        super().advance_to(t)
        if self.t > t0:
            self._ledger.idle(self.label, self.t - t0)


class DecodeError(RuntimeError):
    """An exception raised from inside one decode slot's turn —
    ``rid`` names the row whose computation failed. The session's
    drive loop catches it, tears down exactly that row (pages freed,
    slot released, metrics record and trace root moved out — the
    request fails over, it is not lost) and leaves every other row's
    stream untouched. Anything raising from a decode turn that is NOT
    a DecodeError still propagates: an unattributable backend failure
    must stay loud."""

    def __init__(self, rid: str, msg: Optional[str] = None):
        super().__init__(msg or f"decode failed for row {rid!r}")
        self.rid = rid


class UnstampedHandoffError(ValueError):
    """A ``KVHandoff`` reached placement or import WITHOUT its source
    geometry stamped (``page_size``/``tp`` at their vacuous dataclass
    defaults). Every exporter stamps real geometry + codec
    (``_handoff_sink``); an unstamped handoff means hand-built plumbing
    skipped it, and silently matching it against candidates would
    either transform against garbage or — the pre-hetero failure —
    match nothing and quietly fail every request. Refuse loudly
    instead."""

    def __init__(self, h, msg: Optional[str] = None):
        rid = getattr(getattr(h, "req", None), "rid", None)
        super().__init__(msg or (
            f"handoff {rid!r} is unstamped (page_size="
            f"{getattr(h, 'page_size', None)!r}, "
            f"tp={getattr(h, 'tp', None)!r}) — the exporter must "
            "stamp real source geometry/tp/codec before a handoff "
            "can be placed or imported"))
        self.rid = rid


class Policy:
    """Routes one admission wave. ``ctx`` carries the wave statistics
    (lengths, capacity, shared_prefix, expect_churn) plus engine state
    (active_paged). Returns (backend, reason)."""

    name = "base"

    def route(self, wave: List[Request], ctx: dict):
        raise NotImplementedError

    def spec_route(self, r: Request, cfg) -> Tuple[bool, str]:
        """The PER-REQUEST adaptive speculative rule (``RoutedPolicy``
        applies it on a spec-configured engine; every policy shares
        this default, and a custom policy may override it): a request
        decodes speculatively only when its traffic can absorb a
        missed draft — low priority AND a loose (or absent) deadline.
        Tight/high-priority rows keep the plain fixed-latency decode
        path regardless of how well the draft is doing. Returns
        (eligible, rule) with the clause that fired, the same
        ``explain=`` discipline as ``route_decode``."""
        if r.priority > cfg.max_priority:
            return False, (f"priority {r.priority} > spec ceiling "
                           f"{cfg.max_priority} (latency-critical "
                           "traffic decodes plain)")
        if r.deadline_ms is not None \
                and r.deadline_ms < cfg.loose_deadline_ms:
            return False, (f"deadline {r.deadline_ms}ms < loose "
                           f"floor {cfg.loose_deadline_ms}ms (a "
                           "tight deadline cannot absorb a missed "
                           "draft window)")
        return True, "loose-deadline/low-priority (spec-eligible)"


class FixedPolicy(Policy):
    """Everything to one backend — the bench's ablation arms."""

    def __init__(self, backend: str):
        if backend not in ("dense", "paged"):
            raise ValueError(f"backend {backend!r}")
        self.backend = backend
        self.name = backend

    def route(self, wave, ctx):
        return self.backend, f"fixed policy ({self.backend}-only)"


class RoutedPolicy(Policy):
    """The default: delegate to ``route_decode`` (the chip-measured
    policy behind ``_Serving.pick``), with one engine-level rule layered
    on top — a wave arriving while paged requests are mid-flight joins
    the running batch rather than stalling it behind a dense wave (one
    chip serializes programs; parking N streaming requests to run a
    wave start-to-finish would torch their TPOT)."""

    name = "routed"

    def route(self, wave, ctx):
        if ctx.get("active_paged", 0) > 0:
            return "paged", ("join-active-batch (paged requests "
                             "mid-flight; a dense wave would stall "
                             "their token streams)")
        return route_decode([len(r.prompt) for r in wave],
                            ctx["capacity"],
                            shared_prefix=ctx["shared_prefix"],
                            expect_churn=ctx["expect_churn"],
                            explain=True)


def make_policy(spec) -> Policy:
    if isinstance(spec, Policy):
        return spec
    if spec == "routed":
        return RoutedPolicy()
    return FixedPolicy(spec)


def _coerce_paged_only(policy, what: str, why: str):
    """Paged-only feature coercion (tensor parallelism, adapter
    multiplexing): the routed policy — string OR instance — coerces
    to the paged fixed policy, and an explicitly dense one is a
    configuration error at construction, not a NotImplementedError
    mid-serve. A custom Policy object is the caller's responsibility
    to keep paged-only."""
    if policy == "routed" or isinstance(policy, RoutedPolicy):
        return "paged"
    if policy == "dense" or (isinstance(policy, FixedPolicy)
                             and policy.backend == "dense"):
        raise ValueError(f"policy='dense' {what}: {why}")
    return policy


@dataclasses.dataclass
class ServeResult:
    policy: str
    outputs: Dict[str, List[int]]   # rid -> generated tokens (in order)
    metrics: MetricsCollector
    decisions: List[dict]           # one per admission wave
    slot_log: List[tuple]           # (t, "acquire"|"release", rid, slot)
    prefix_cached: Dict[str, int]   # rid -> prompt tokens prefix-cache hit
    pages_total: int
    pages_free_end: int             # RECLAIMABLE pages at run end:
    # free list + evictable LRU (a retained prefix page is capacity,
    # not a leak — it frees itself under allocation pressure)
    scheduler: str = "fifo"         # admission discipline that ran
    shed: Dict[str, str] = dataclasses.field(default_factory=dict)
    # rid -> shed reason (QoS scheduler only; FIFO never sheds)
    trace: Optional[object] = None  # obs.Tracer when the run traced
    prefill_tokens: int = 0         # prompt tokens actually prefilled
    # (padded, minus the cache-resumed chunks) across paged admits
    cache_stats: Dict = dataclasses.field(default_factory=dict)
    # PagedKVCache.cache_stats() at run end + "invariant_ok": the
    # resident+evictable+free == pool-size census, sampled every
    # engine turn
    replica: Optional[str] = None   # cluster replica name (a lone
    # engine leaves it None and its logs stay byte-identical to PR 4)
    incidents: Optional[List] = None  # obs.slo.Incident list when the
    # run carried an SLO monitor; None otherwise. Never serialized by
    # save_log — monitor-on logs stay byte-identical to monitor-off
    # (the obs_slo gate's identity clause); the incident JSONL is the
    # monitor's own IncidentLog.save
    adapter_stats: Optional[Dict] = None  # AdapterCache.cache_stats()
    # + "invariant_ok" (the ADAPTER slot census alone, sampled every
    # engine turn — independent of cache_stats' pool flag, so each
    # census names its own subsystem) when the run served adapters;
    # None single-model — the result shape every pre-adapter consumer
    # sees is unchanged
    spec_stats: Optional[Dict] = None  # the speculative route's
    # per-run evidence (rounds, draft tokens proposed/accepted,
    # acceptance EWMA, and the deterministic flip log with explain
    # rules) when the engine carried spec=; None otherwise — the
    # result shape every pre-spec consumer sees is unchanged
    kv_quant_stats: Optional[Dict] = None  # the quantized page
    # tier's per-run evidence (mode, quantized-page count, the
    # stored-byte census, and under 'pressure' the deterministic
    # actuation flip log + pages compacted) when the engine carried
    # kv_quant=; None otherwise — the result shape every pre-quant
    # consumer sees is unchanged
    overhead: Optional[Dict] = None  # measured-clock runs only: the
    # host-overhead decomposition {run_wall_s, device_wall_s,
    # engine_host_frac} — the fraction of run wall time NOT covered by
    # in-flight device work (dispatch-ahead shrinks it). None on fixed
    # clocks and sessions; never serialized by save_log, so logs stay
    # byte-identical either way
    hostmem_stats: Optional[Dict] = None  # the host-DRAM arena tier's
    # per-run evidence (arena census + transfer counts, preempt/restore
    # tallies, spilled-page census) when the engine carried hostmem=;
    # None otherwise — the result shape every pre-hostmem consumer
    # sees is unchanged
    pages_spilled: Optional[int] = None  # pages parked host-side at
    # run end — spilled pages are NOT device capacity (pages_free_end
    # never counts them; spill ≠ leak, the PR-5 retention rule one
    # tier down), but an offline replay needs the census to balance.
    # None at hostmem=None keeps save_log byte-identical
    grammar_stats: Optional[Dict] = None  # GrammarCache.cache_stats()
    # + "invariant_ok" (the grammar slot census alone — resident +
    # evictable + free == n_slots-1, sampled every engine turn) when
    # the run served constrained streams; None at grammar=None — the
    # result shape every pre-grammar consumer sees is unchanged
    cost_stats: Optional[Dict] = None  # obs.ledger.CostLedger
    # cost_stats() for this engine's book (elapsed/idle/attributed
    # unit totals, per-kind breakdown, page-turn integral, and the
    # two conservation-audit flags) when the run carried ledger=;
    # None otherwise — never serialized by save_log, so ledger-on
    # logs stay byte-identical to ledger-off

    def report(self, **slo) -> dict:
        return self.metrics.report(**slo)

    def save_log(self, path: str) -> str:
        """Dump the engine's decision + slot + shed log as JSONL, so an
        overload incident can be replayed offline (``load_engine_log``
        round-trips it). One ``meta`` line, then one line per wave
        decision, slot acquire/release, and shed. A cluster replica's
        result stamps its ``replica`` name on EVERY record, so logs
        from N replicas can be concatenated into one cluster incident
        file without losing attribution; with ``replica`` unset
        (single-engine runs) the format is byte-identical to PR 4.

        The write is ATOMIC (tmp + ``os.replace``, the same discipline
        as ``framework/io.py`` ``save``): a crash or serialization
        error mid-dump can never leave a truncated file where the
        previous incident log used to be."""
        tag = {} if self.replica is None else {"replica": self.replica}
        # spilled-page census joins the meta line ONLY on hostmem runs
        # (key absent otherwise — legacy logs stay byte-identical)
        spill = {} if self.pages_spilled is None \
            else {"pages_spilled": self.pages_spilled}
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write(json.dumps({
                    "kind": "meta", "policy": self.policy,
                    "scheduler": self.scheduler,
                    "pages_total": self.pages_total,
                    "pages_free_end": self.pages_free_end,
                    **spill, **tag})
                    + "\n")
                for d in self.decisions:
                    f.write(json.dumps({"kind": "decision", **d, **tag})
                            + "\n")
                for t, ev, rid, slot in self.slot_log:
                    f.write(json.dumps({"kind": "slot", "t": t,
                                        "event": ev, "rid": rid,
                                        "slot": slot, **tag}) + "\n")
                for rid, reason in self.shed.items():
                    f.write(json.dumps({"kind": "shed", "rid": rid,
                                        "reason": reason, **tag})
                            + "\n")
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        return path


def load_engine_log(path: str) -> dict:
    """Parse a ``ServeResult.save_log`` JSONL back into
    ``{"meta", "decisions", "slot_log", "shed"}`` with the engine's
    in-memory types (slot entries as ``(t, event, rid, slot)``
    tuples), so offline analysis sees what the live run saw. Records
    carrying the optional ``replica`` field (cluster logs, possibly
    several replicas' files concatenated) keep it: decisions retain
    their ``replica`` key, slot entries become 5-tuples
    ``(t, event, rid, slot, replica)``, and sheds map
    ``rid -> (reason, replica)``; replica-less logs load exactly as
    before.

    A log whose FINAL line is torn mid-record — the file a crashing
    process leaves behind when the write was not atomic — loads with a
    warning and returns the valid prefix (the incident evidence that
    survived); a malformed line anywhere EARLIER is still a loud
    error, because a mid-file tear means the file is not an engine
    log (``workload.iter_jsonl_tolerant`` is the shared policy)."""
    out: dict = {"meta": None, "decisions": [], "slot_log": [],
                 "shed": {}}
    for d in iter_jsonl_tolerant(path):
        kind = d.pop("kind", None)
        rep = d.get("replica")
        if kind == "meta":
            out["meta"] = d
        elif kind == "decision":
            out["decisions"].append(d)
        elif kind == "slot":
            row = (d["t"], d["event"], d["rid"], d["slot"])
            out["slot_log"].append(row if rep is None
                                   else row + (rep,))
        elif kind == "shed":
            out["shed"][d["rid"]] = d["reason"] if rep is None \
                else (d["reason"], rep)
        else:
            raise ValueError(f"engine log line has unknown kind "
                             f"{kind!r}")
    return out


def _jit_cache_size(fn) -> Optional[int]:
    """Entry count of a jax.jit program cache. A python shim that
    advertises its inner jitted programs via ``_jit_inner`` (the
    chunked-prefill wrapper) reports their summed count; anything
    else non-jitted reports None (detection off, never wrong)."""
    try:
        return int(fn._cache_size())
    except Exception:
        pass
    inner = getattr(fn, "_jit_inner", None)
    if inner:
        sizes = [_jit_cache_size(f) for f in inner]
        if any(s is None for s in sizes):
            return None
        return sum(sizes)
    return None


class _SpecState:
    """Per-run adaptive state of the speculative route: the measured
    acceptance EWMA, the enable/latch flags, and the deterministic
    flip log. One per ``run()``/session — two seeded replays flip at
    identical virtual times with identical rules."""

    __slots__ = ("cfg", "enabled", "latched", "ewma", "rounds",
                 "samples", "proposed", "accepted", "flips")

    def __init__(self, cfg):
        self.cfg = cfg
        self.enabled = True
        self.latched = False   # acceptance-floor kill: plain for the
        # rest of the run (no spec rounds run -> no new evidence
        # could ever clear it, so the latch is honest, not lazy)
        self.ewma: Optional[float] = None
        self.rounds = 0        # row-rounds (one per row per turn)
        self.samples = 0       # EWMA samples (one per spec TURN) —
        # the min_rounds guard counts THESE: a busy first turn is
        # still one sample, and one unlucky sample must not clear
        # the cold-start guard just because eight rows shared it
        self.proposed = 0
        self.accepted = 0
        self.flips: List[dict] = []

    def note(self, rows: int, proposed: int, accepted: int):
        """One spec TURN's evidence (``rows`` rows each ran one
        draft/verify round). The EWMA samples per turn — per-row
        sampling would weight busy turns quadratically."""
        self.rounds += rows
        self.proposed += proposed
        self.accepted += accepted
        if proposed > 0:
            self.samples += 1
            rate = accepted / proposed
            a = self.cfg.ewma_alpha
            self.ewma = rate if self.ewma is None \
                else (1 - a) * self.ewma + a * rate

    def stats(self) -> dict:
        return {
            "rounds": self.rounds,
            "turns": self.samples,
            "draft_tokens_proposed": self.proposed,
            "draft_tokens_accepted": self.accepted,
            "acceptance_rate": round(
                self.accepted / self.proposed, 4)
            if self.proposed else None,
            "acceptance_ewma": round(self.ewma, 4)
            if self.ewma is not None else None,
            "enabled_end": self.enabled,
            "latched": self.latched,
            "flips": list(self.flips),
        }


class _PagedRow:
    __slots__ = ("req", "slot", "tok", "out", "eff", "done", "t0",
                 "aslot", "spec", "prev", "sprop", "sacc",
                 "gslot", "gname", "gaut", "gstate", "gmasked")

    def __init__(self, req: Request, slot: int, first_tok: int,
                 t0: float = 0.0, aslot: int = 0, spec: bool = False,
                 prev: int = 0, gslot: int = 0,
                 gname: Optional[str] = None, gaut=None,
                 gstate: int = 0):
        self.req = req
        self.slot = slot
        self.tok = first_tok
        self.out = [first_tok]
        self.t0 = t0  # admit time (slot-occupancy span start)
        self.aslot = aslot  # adapter-bank slot (0 = identity)
        self.spec = spec    # spec-eligible (admission-time verdict)
        self.prev = prev    # token at position lengths-1 (the spec
        # draft's two-token feed re-consumes it; plain rows never
        # read it)
        self.sprop = 0      # draft tokens proposed for this row
        self.sacc = 0       # draft tokens accepted for this row
        self.gslot = gslot    # grammar-bank slot (0 = all-allow)
        self.gname = gname    # schema name (None = free-running)
        self.gaut = gaut      # CompiledGrammar (host transition table)
        self.gstate = gstate  # current DFA state (host-advanced per
        # emitted token; the decode batch carries flat_id(gslot,
        # gstate) as jit DATA)
        self.gmasked = 0.0    # sum of per-token masked-vocab fracs
        cancel = req.cancel_after if req.cancel_after is not None \
            else 10 ** 9
        self.eff = min(req.max_new_tokens, cancel)
        self.done = False


class _PrefillingRow:
    """One request in the ASYNC PREFILL LANE: admitted (pages + slot
    reserved, ``book.lengths`` set) but not yet decoding — its prefill
    runs one chunk per lane step, between the engine's decode turns,
    so pending prefill can never monopolize a turn. ``next_chunk`` is
    the absolute chunk index the next lane step computes (the cached
    resume already skipped); when it reaches ``n_chunks`` the request
    enters its decode slot (or exports as a KV handoff on a
    prefill-role session)."""

    __slots__ = ("req", "slot", "t_admit", "n_cached", "resume", "T",
                 "next_chunk", "n_chunks", "run_chunks", "toks", "pt",
                 "skipped", "aslot", "spec", "gslot", "gname", "gaut",
                 "gstate")

    def __init__(self, req: Request, slot: int, t_admit: float,
                 n_cached: int, resume: int, T: int, chunk: int,
                 toks, pt, aslot: int = 0, spec: bool = False,
                 gslot: int = 0, gname: Optional[str] = None,
                 gaut=None, gstate: int = 0):
        self.req = req
        self.slot = slot
        self.t_admit = t_admit
        self.n_cached = n_cached
        self.resume = resume          # chunk-aligned cached skip
        self.T = T                    # padded prompt length
        self.next_chunk = min(resume, T - chunk) // chunk
        self.n_chunks = T // chunk
        # chunks this request actually computes (cache skip excluded)
        # — the denominator for flat-cost-per-chunk pricing
        self.run_chunks = self.n_chunks - self.next_chunk
        self.toks = toks              # (1, T) padded prompt tokens
        self.pt = pt                  # (1, W) page table row
        self.skipped = 0              # times passed over by a shorter
        # entry — the anti-starvation aging counter
        self.aslot = aslot            # adapter-bank slot (0 = identity)
        self.spec = spec              # spec-eligible (admission-time)
        self.gslot = gslot            # grammar-bank slot (0=all-allow)
        self.gname = gname            # schema name (None = free)
        self.gaut = gaut              # CompiledGrammar
        self.gstate = gstate          # DFA state the FIRST emitted
        # token will be masked by (resume-walked for preempted rows)

    def remaining_chunks(self) -> int:
        return self.n_chunks - self.next_chunk


class _AheadState:
    """The dispatch-ahead turn's double buffer: the decode batch
    dispatched at the END of turn t (before turn t's host bookkeeping
    finished), plus the roster FINGERPRINT it was built from. Turn
    t+1 serves the stashed result only when its roster fingerprint
    still matches — any admission, finish, eviction or token change in
    between discards the stash and re-dispatches the identical work,
    so outputs can never diverge. The stash never holds pools: the
    pool buffers were donated through (and rebound at) dispatch time,
    exactly like a synchronous call."""

    __slots__ = ("emits", "fp", "wall0")

    def __init__(self):
        self.emits = None   # stashed decode_n emits (device handle)
        self.fp = None      # roster fingerprint the dispatch assumed
        self.wall0 = 0.0    # perf_counter at dispatch (overlap span)

    def clear(self):
        self.emits = None
        self.fp = None


@dataclasses.dataclass
class KVHandoff:
    """A finished prefill MOVING from a prefill-role worker to a
    decode worker: the prompt's KV page chain (exported from the
    source pool along the page axis), the greedy first token the
    prefill produced, and the timestamps the destination's metrics
    record needs to stay honest (``t_admit`` — the admission that
    actually happened, on the source; ``t_first`` — when the first
    token materialized; ``t_ready`` — when the chain left the source,
    the moment the per-page transfer cost starts ticking). The
    request's metrics record and trace root move WITH the handoff
    (PR-7 move-not-duplicate discipline): the source forgets it, the
    destination re-records it, and the cluster census counts it
    exactly once. ``t_arrive`` is stamped by the router:
    ``t_ready + n_pages * kv_transfer_unit`` on the shared timeline.

    ``page_size``/``tp``/``kv_quant`` describe the SOURCE layout of
    ``kv_data``. Since the hetero PR they are no longer placement
    FILTERS: a destination whose geometry/mesh/codec differ runs the
    priced ``kv_reshard``/``kv_repage``/``kv_transcode`` transform
    steps at import (``ServingEngine.handoff_steps`` names which, and
    which pairings still refuse), mutating these stamps to the
    destination's values as each step lands. An exporter that leaves
    them at the vacuous defaults gets an ``UnstampedHandoffError`` at
    placement/import — loudly, never a silent match-nothing."""

    req: Request
    first_tok: int
    n_pages: int                      # exported chain length (pages)
    kv_data: object                   # opaque per-factory page data
    n_cached: int                     # source-side prefix-cache hit
    t_admit: float
    t_first: float
    t_ready: float
    replica_from: Optional[str] = None
    t_arrive: float = 0.0             # router-stamped delivery time
    page_size: int = 0                # source page geometry; a
    # destination on a different geometry re-pages the chain at import
    # (priced kv_repage). 0 = unstamped -> UnstampedHandoffError.
    tp: int = 1                       # source tensor-parallel degree:
    # exported page content is head-sharded over the source mesh; a
    # destination on a different mesh width gathers the shards into
    # the canonical layout at import (priced kv_reshard) and its
    # scatter re-splits under its own pool sharding
    kv_quant: Optional[str] = None    # source kv-quant mode: the
    # exported page data is tier-shaped ('pressure' chains carry the
    # dual-arena slices + tier bits, 'int8' chains carry scales). A
    # full-precision chain transcodes to an int8/pressure destination
    # at import (priced kv_transcode, scales + tier bits stamped);
    # quantized sources only adopt same-codec (handoff_steps refuses
    # the lossy/unliftable pairings)
    layout: str = "head_major"        # canonical-layout descriptor of
    # kv_data: "head_major" — every leaf page-indexed on axis 2 with
    # the kv-head axis whole in the GLOBAL shape (the llama pools,
    # sharded or not: kv_reshard gathers the shards into one host
    # view of this same layout, so the descriptor survives every
    # transform step); "tokens" — the sim's (n_pages, page_size)
    # token rows. Transforms validate against it instead of guessing
    # from array ranks.
    quant_pages: Tuple[int, ...] = () # chain positions (indices into
    # the exported chain, NOT pool page ids) that sat in the int8
    # tier at export — the importer mirrors them into its own
    # bookkeeper so its byte census prices the adopted chain right


class ServingEngine:
    """Replay a trace (workload.Request list) through the serving stack.

    ``slots``: concurrent paged decode rows (the fixed compiled batch
    shape; empty slots ride along as length-0 page-0 rows) and the dense
    routing capacity. ``decode_chunk``: decode steps fused per scheduler
    turn via ``decode_n`` (dispatch amortization; tokens within a chunk
    share a timestamp). ``serving``: a prebuilt
    ``llama_serving_decode_factory(...)`` to share compiled programs
    across engines (its build config must carry ``chunked_prefill`` —
    the prefix-cache resume path needs chunked prefill).
    ``scheduler``: None (FIFO, byte-identical to PR 2), ``"qos"``, or
    a configured ``QoSScheduler`` — the SLO-aware front door (priority
    + weighted-fair admission, deadline feasibility, shedding and
    degradation, timeouts).
    ``trace``: None (tracing off — the default, zero spans recorded),
    an ``obs.Tracer`` (caller keeps the handle; cleared at each run's
    start), or a path string (a fresh tracer exports chrome://tracing
    JSON there after every run). Spans ride the run's VIRTUAL clock:
    request roots on one track per tenant, occupancy on one track per
    decode slot, prefill/decode work on the engine track, scheduler
    decisions + jit recompiles as instants. Outputs, metrics records
    and logs are byte-identical with tracing on or off.
    ``prefix_cache``: True (default) makes prefix reuse AUTOMATIC for
    every paged admit — acquire before allocate, register after
    prefill, no ``prefix_group`` tag needed (the tag stays a routing
    hint only); freed prompt pages are RETAINED in the pool's
    evictable LRU, so a recurring system prompt skips its cached
    prefill chunks even after every earlier sharer finished. False
    disables all acquisition/retention (the bench's cache-off arm).
    """

    # async-lane anti-starvation: the oldest lane entry runs its next
    # chunk after being passed over this many consecutive times by
    # shorter entries, so a long prefill's first token is bounded by
    # ~run_chunks * (limit+1) lane chunks REGARDLESS of how long a
    # sustained short-prompt stream lasts (pure
    # shortest-remaining-first would starve it for the stream's whole
    # lifetime, pinning its slot and pages). The default trades a
    # loose bound for zero short-prompt TTFT tax on the gated
    # prefill-heavy trace; subclasses may tighten it.
    _LANE_STARVE_LIMIT = 11

    def __init__(self, model=None, *, serving=None, slots: int = 4,
                 max_len: int = 64, page_size: int = 8,
                 n_pool_pages: Optional[int] = None, policy="routed",
                 admission: Optional[BatchingConfig] = None,
                 decode_chunk: int = 1, clock: str = "measured",
                 fixed_costs: Optional[dict] = None,
                 eos_token_id: Optional[int] = None,
                 kv_cache_dtype: Optional[str] = None,
                 scan_layers: bool = True,
                 expect_churn: Optional[bool] = None,
                 scheduler=None, trace=None,
                 prefix_cache: bool = True,
                 prefill_chunk_budget: Optional[int] = None,
                 slo=None, tp=None, adapters=None, lora=None,
                 spec=None, spec_draft=None, kv_quant=None,
                 kv_quant_budget=None, ragged_prefill: bool = False,
                 dispatch_ahead: bool = False, hostmem=None,
                 grammar=None, grammar_config=None,
                 adapter_schemas=None, ledger=None):
        # ``tp``: None (byte-identical to the single-device engine —
        # outputs, slot logs, metrics records, registry contents), a
        # TPConfig, or an int degree. With a MODEL it is threaded into
        # the factory build (weights + pools placed once, sharded);
        # with a PREBUILT factory the factory's own tp_ is
        # authoritative — passing a conflicting tp here is an error,
        # because arrays cannot be re-sharded after the build.
        # ``adapters``: None (byte-identical to the single-model
        # engine) or an AdapterStore / {name: deltas} dict — the
        # multi-model LoRA registry. Needs a lora-enabled factory:
        # with a MODEL, pass ``lora=LoRAConfig(...)|(n_slots, rank)``
        # and it is threaded into the build; with a PREBUILT factory
        # the factory's own lora_ is authoritative (conflicts error,
        # like tp). Per-request ``Request.adapter`` names the delta
        # set; adapter weights page host->device through a budgeted
        # ``AdapterCache`` (LRU retention, pin-while-in-flight) and
        # every mix of adapters decodes through ONE fixed-shape
        # compiled batch.
        tp = as_tp_config(tp)
        lora = as_lora_config(lora)
        # ``spec``: None (byte-identical to the plain engine —
        # outputs, slot logs, decisions, metrics records, report
        # keys, registry contents), a SpecConfig, or an int draft
        # window. The SPECULATIVE route: eligible rows (see
        # ``Policy.spec_route``) decode through one batched
        # draft/verify round per turn instead of ``decode_n``, with
        # greedy acceptance keeping every emitted token EXACTLY the
        # target's greedy token; the route falls back to plain decode
        # when the measured acceptance EWMA sinks below the floor or
        # while an overload incident delivered through
        # ``QoSScheduler.note_incident`` stays open. Needs a
        # spec-capable factory: with a MODEL, pass the draft model as
        # ``spec_draft=``; with a PREBUILT factory, build it with
        # ``llama_serving_decode_factory(draft=...)`` (or
        # ``SimServing(spec_accept=...)``). Draft and target share
        # ONE PagedKVCache page-id space — draft K/V lands in its own
        # pool arrays at the target's page ids, so prefix caching and
        # eviction recycle both in lockstep.
        # ``kv_quant``: None (byte-identical to the plain engine —
        # outputs, logs, metrics records, report keys, registry
        # contents), 'int8' (EVERY page stored quantized with
        # per-slot scales — the pool is ~half the fp bytes, so the
        # same HBM budget holds ~2x the pages), or 'pressure' (pages
        # stay full-precision while hot; pages parked in the
        # evictable LRU compact to an int8 tier instead of being
        # freed — under ``kv_quant_budget=`` stored bytes at
        # allocation, and while a ``pool_bytes_per_device``
        # ThresholdRule incident delivered through
        # ``QoSScheduler.note_incident`` stays open). With a MODEL
        # the mode is threaded into the factory build; with a
        # PREBUILT factory the factory's own kv_quant_ is
        # authoritative (conflicts error, like tp/lora).
        # ``grammar``: None (byte-identical to the free-running
        # engine — outputs, slot logs, metrics records, report keys,
        # registry contents) or a GrammarStore / {name: schema-dict |
        # EBNF-str} registry — CONSTRAINED decoding. Needs a
        # grammar-enabled factory: with a MODEL, pass
        # ``grammar_config=GrammarConfig(...)|(n_slots, max_states)``
        # and it is threaded into the build; with a PREBUILT factory
        # the factory's own grammar_ is authoritative (conflicts
        # error, like tp/lora). Per-request ``Request.schema`` names
        # the grammar; compiled automata page into a device mask bank
        # through a budgeted ``GrammarCache`` (LRU retention,
        # pin-while-in-flight) and every mix of constrained and free
        # rows decodes through ONE fixed-shape compiled batch — the
        # per-row DFA state rides as jit data, never a recompile.
        # ``adapter_schemas``: {adapter_name: schema_name} — the
        # per-adapter DEFAULT schema; a request naming that adapter
        # (with Request.schema unset) decodes constrained under it.
        grammar_config = as_grammar_config(grammar_config)
        spec = as_spec_config(spec)
        if serving is None:
            if model is None:
                raise ValueError("pass a model or a prebuilt serving "
                                 "factory")
            if max_len % page_size:
                raise ValueError(f"max_len {max_len} must be a multiple "
                                 f"of page_size {page_size}")
            if spec is not None and spec_draft is None:
                raise ValueError(
                    "spec= with a model needs the draft model too "
                    "(spec_draft=), or pass a prebuilt spec-capable "
                    "factory (llama_serving_decode_factory("
                    "draft=...))")
            if spec_draft is not None and spec is None:
                raise ValueError(
                    "spec_draft= without spec= would build the whole "
                    "draft decode stack (programs + a full-size "
                    "draft KV pool) that nothing ever uses — pass "
                    "spec=SpecConfig(...) (or True) to serve "
                    "speculatively, or drop the draft")
            if n_pool_pages is None:
                # page 0 is the reserved padding page; each slot may
                # need max_len/page_size pages
                n_pool_pages = slots * (max_len // page_size) + 1
            serving = llama_serving_decode_factory(
                model, max_len=max_len, page_size=page_size,
                n_pool_pages=n_pool_pages, kv_cache_dtype=kv_cache_dtype,
                batch_capacity=slots, scan_layers=scan_layers,
                chunked_prefill=page_size, tp=tp, lora=lora,
                draft=spec_draft, kv_quant=kv_quant,
                grammar=grammar_config)
        else:
            if spec_draft is not None:
                raise ValueError(
                    "spec_draft= is ignored with a prebuilt factory "
                    "— build it spec-capable instead ("
                    "llama_serving_decode_factory(draft=...) / "
                    "SimServing(spec_accept=...))")
            max_len = serving.max_len_
            page_size = serving.page_size_
            n_pool_pages = serving.n_pool_pages_
            fac_tp = getattr(serving, "tp_", None)
            if tp is not None and fac_tp != tp:
                raise ValueError(
                    f"tp={tp} conflicts with the prebuilt factory's "
                    f"tp_={fac_tp} — a factory's placement is fixed "
                    "at build; pass tp to the factory (or the model "
                    "path) instead")
            tp = fac_tp
            fac_lora = getattr(serving, "lora_", None)
            if lora is not None and fac_lora != lora:
                raise ValueError(
                    f"lora={lora} conflicts with the prebuilt "
                    f"factory's lora_={fac_lora} — the adapter bank "
                    "is sized at build; pass lora to the factory (or "
                    "the model path) instead")
            lora = fac_lora
            fac_q = getattr(serving, "kv_quant_", None)
            if kv_quant is not None and fac_q != kv_quant:
                raise ValueError(
                    f"kv_quant={kv_quant!r} conflicts with the "
                    f"prebuilt factory's kv_quant_={fac_q!r} — the "
                    "page-tier layout is fixed at build; pass "
                    "kv_quant to the factory (or the model path) "
                    "instead")
            kv_quant = fac_q
            fac_g = getattr(serving, "grammar_", None)
            if grammar_config is not None and fac_g != grammar_config:
                raise ValueError(
                    f"grammar_config={grammar_config} conflicts with "
                    f"the prebuilt factory's grammar_={fac_g} — the "
                    "mask bank is sized at build; pass grammar_config "
                    "to the factory (or the model path) instead")
        # --- multi-model adapter serving (inert at adapters=None) ---
        self.lora = getattr(serving, "lora_", None)
        if adapters is not None and not isinstance(adapters,
                                                  AdapterStore):
            adapters = AdapterStore(dict(adapters))
        if adapters is not None and self.lora is None:
            raise ValueError(
                "adapters= needs a lora-enabled serving factory "
                "(llama_serving_decode_factory(lora=...) or "
                "SimServing(lora_slots=...)) — the adapter bank is "
                "part of the compiled program's inputs")
        self._adapter_store = adapters
        self._g_adapter_resident = None
        self._ctr_adapter_hits = None
        self._ctr_adapter_uploads = None
        if adapters is not None:
            # created ONLY when multi-model serving is configured, so
            # single-model runs leave no trace in the registry (PR-5
            # convention)
            self._g_adapter_resident = obs_metrics.REGISTRY.gauge(
                "serving_adapter_resident",
                "LoRA adapters resident in the device bank "
                "(pinned + retained)")
            self._ctr_adapter_hits = obs_metrics.REGISTRY.counter(
                "serving_adapter_hits_total",
                "adapter admissions served from the resident bank")
            self._ctr_adapter_uploads = obs_metrics.REGISTRY.counter(
                "serving_adapter_uploads_total",
                "host->device adapter delta uploads")
            # multi-model serving is paged-only, exactly like tp: the
            # dense wave cache has no adapter bank
            policy = _coerce_paged_only(
                policy, "with adapters",
                "the dense backend holds no adapter bank")
        # --- constrained decoding (inert at grammar=None) -----------
        self.grammar_cfg = getattr(serving, "grammar_", None)
        if grammar is not None and not isinstance(grammar,
                                                  GrammarStore):
            grammar = GrammarStore(dict(grammar))
        if grammar is not None and self.grammar_cfg is None:
            raise ValueError(
                "grammar= needs a grammar-enabled serving factory "
                "(llama_serving_decode_factory(grammar=...) or "
                "SimServing(grammar_slots=...)) — the mask bank is "
                "part of the compiled program's inputs")
        self._grammar_store = grammar
        # host-side compiled-automaton memo, shared by every run's
        # GrammarCache AND the scheduler's min-tokens probe: one
        # schema compiles ONCE per engine no matter how many runs,
        # sessions or probes touch it
        self._dfa_memo: Dict[str, object] = {}
        self._adapter_schemas: Dict[str, str] = {}
        if adapter_schemas:
            if grammar is None:
                raise ValueError(
                    "adapter_schemas= names default schemas but no "
                    "grammar= registry was given to resolve them")
            if adapters is None:
                raise ValueError(
                    "adapter_schemas= without adapters= — there are "
                    "no adapters to default")
            for a, gname in dict(adapter_schemas).items():
                if a not in adapters:
                    raise ValueError(
                        f"adapter_schemas names unknown adapter "
                        f"{a!r} (registered: {adapters.names()})")
                if gname not in grammar:
                    raise ValueError(
                        f"adapter_schemas[{a!r}] names unknown "
                        f"schema {gname!r} (registered: "
                        f"{grammar.names()})")
            self._adapter_schemas = dict(adapter_schemas)
        self._ctr_grammar_hits = None
        self._ctr_grammar_compiles = None
        if grammar is not None:
            # created ONLY when constrained decoding is configured,
            # so free-running runs leave no trace in the registry
            # (PR-5 convention)
            self._ctr_grammar_hits = obs_metrics.REGISTRY.counter(
                "serving_grammar_hits_total",
                "constrained admissions served from the resident "
                "mask bank")
            self._ctr_grammar_compiles = obs_metrics.REGISTRY.counter(
                "serving_grammar_compiles_total",
                "grammar automaton compiles + mask-bank uploads")
            # constrained decoding is paged-only, exactly like tp and
            # adapters: the dense wave cache has no grammar mask bank
            policy = _coerce_paged_only(
                policy, "with grammar",
                "the dense backend holds no grammar mask bank")
        # --- speculative serving (inert at spec=None) ---------------
        self.spec = spec
        self._spec_parts = getattr(serving, "spec_parts", None)
        self._ctr_spec_rounds = None
        self._ctr_draft_proposed = None
        self._ctr_draft_accepted = None
        self._ctr_spec_flips = None
        if spec is not None:
            if self._spec_parts is None:
                raise ValueError(
                    "spec= needs a spec-capable serving factory "
                    "(llama_serving_decode_factory(draft=...) or "
                    "SimServing(spec_accept=...)) — the draft "
                    "programs and its paged pool are built with the "
                    "factory")
            if adapters is not None:
                raise ValueError(
                    "spec= does not compose with adapters= yet — the "
                    "draft has no adapter bank (serve spec engines "
                    "single-model)")
            # speculative serving is paged-only, exactly like tp and
            # adapters: the dense wave cache has no draft/verify
            # program
            policy = _coerce_paged_only(
                policy, "with spec",
                "the dense backend holds no draft/verify program")
            if not hasattr(serving, "_live_spec_pools"):
                # the draft pool buffers are DONATED through every
                # draft prefill / spec round, like the target pools —
                # the live buffers ride the shareable serving object
                serving._live_spec_pools = self._spec_parts[2]
            # created ONLY when a spec route is configured, so plain
            # runs leave no trace in the registry (PR-5 convention)
            _sc = obs_metrics.REGISTRY.counter
            self._ctr_spec_rounds = _sc(
                "serving_spec_rounds_total",
                "speculative draft/verify rounds run (one per spec "
                "row per turn)")
            self._ctr_draft_proposed = _sc(
                "serving_draft_tokens_proposed_total",
                "draft tokens proposed for target verification")
            self._ctr_draft_accepted = _sc(
                "serving_draft_tokens_accepted_total",
                "draft tokens the target verification accepted")
            self._ctr_spec_flips = {
                to: _sc("serving_spec_flips_total",
                        "adaptive spec-route flips by direction",
                        to=to)
                for to in ("plain", "spec")}
        self.tp = tp
        self.tp_size = tp.size if tp is not None else 1
        if tp is not None:
            # tensor-parallel serving is paged-only (no dense replica
            # exists — see llama_decode.PagedOnlyDense)
            policy = _coerce_paged_only(
                policy, "under tp",
                "a sharded factory holds no dense replica")
        # --- quantized paged KV (inert at kv_quant=None) ------------
        # 'int8': EVERY page stored as (int8, per-slot scale) — the
        # pool arrays are physically ~half the fp bytes, decode reads
        # through the existing dequant path. 'pressure': pages stay
        # full-precision while hot; pages parked in the evictable LRU
        # are COMPACTED to the int8 tier instead of freed — under a
        # byte budget (kv_quant_budget=) at allocation, and whenever a
        # pool_bytes_per_device incident delivered through
        # QoSScheduler.note_incident stays open (capacity degradation
        # one rung BEFORE any shedding tier). kv_quant=None is
        # byte-identical to every earlier PR.
        if kv_quant not in (None, "int8", "pressure"):
            raise ValueError(f"kv_quant {kv_quant!r}: use None, "
                             "'int8' or 'pressure'")
        self.kv_quant = kv_quant
        if kv_quant_budget is not None:
            if kv_quant != "pressure":
                raise ValueError(
                    "kv_quant_budget= only means something under "
                    "kv_quant='pressure' (the stored-byte ceiling "
                    "allocation-time compaction defends); an "
                    "always-int8 pool is already small")
            if kv_quant_budget <= 0:
                raise ValueError("kv_quant_budget must be > 0 bytes")
        self.kv_quant_budget = kv_quant_budget
        self._ctr_compactions = None
        self._ctr_quant_flips = None
        if kv_quant == "pressure":
            if spec is not None:
                raise ValueError(
                    "kv_quant='pressure' does not compose with spec= "
                    "— the draft pool rides the target's page ids "
                    "but carries no page-tier mask (use "
                    "kv_quant='int8')")
            if tp is not None:
                raise ValueError(
                    "kv_quant='pressure' does not compose with tp= — "
                    "the (P,) page-tier mask is a whole-pool jit "
                    "input with no kv-head axis to shard (use "
                    "kv_quant='int8')")
            # pressure-tier serving is paged-only, exactly like tp:
            # the dense wave cache has no page tiers to compact
            policy = _coerce_paged_only(
                policy, "under kv_quant='pressure'",
                "the dense wave cache has no page tiers")
            # created ONLY when the pressure tier is configured, so
            # plain and always-int8 runs leave no trace of them in
            # the registry (PR-5 convention)
            _qc = obs_metrics.REGISTRY.counter
            self._ctr_compactions = _qc(
                "serving_kv_compactions_total",
                "parked full-precision pages compacted to the int8 "
                "tier")
            self._ctr_quant_flips = {
                to: _qc("serving_kv_quant_flips_total",
                        "pressure-tier actuation flips by direction",
                        to=to)
                for to in ("on", "off")}
        if serving.chunked_prefill_ is None:
            raise ValueError("the engine needs a chunked-prefill paged "
                             "backend (llama_serving_decode_factory("
                             "chunked_prefill=<page multiple>)) — "
                             "prefix-cache resume skips whole chunks")
        dense_parts = serving.dense._parts
        if dense_parts.get("rolling"):
            raise ValueError("dense wave batching over a rolling "
                             "(sliding-window) cache is unsupported")
        self.serving = serving
        self.slots = slots
        self.max_len = max_len
        self.page_size = page_size
        self.n_pool_pages = n_pool_pages
        self.W = max_len // page_size  # fixed page-table width
        self.chunk_C = serving.chunked_prefill_
        if clock not in ("measured", "fixed"):
            raise ValueError(f"clock {clock!r}: use 'measured' or "
                             "'fixed'")
        self.policy = make_policy(policy)
        # scheduler=None is the FIFO default and replays PR-2 traces
        # BYTE-IDENTICALLY (the determinism promise above); "qos" or a
        # QoSScheduler instance routes runs through the QoS front door
        if scheduler == "qos":
            scheduler = QoSScheduler()
        if scheduler is not None and not hasattr(scheduler, "select"):
            raise ValueError("scheduler must be None, 'qos', or a "
                             "QoSScheduler-like object with "
                             "enqueue/select/commit")
        self.scheduler = scheduler
        if spec is not None and spec.overload_fallback \
                and scheduler is not None \
                and hasattr(scheduler, "track_overload"):
            # arm the declared overload seam: note_incident then
            # tracks open page-severity incidents so the spec gate's
            # overload_active() probe answers — tracked only when a
            # consumer is armed (the PR-11 hardening discipline)
            scheduler.track_overload = True
        if kv_quant == "pressure" and scheduler is not None \
                and hasattr(scheduler, "track_pressure"):
            # same seam, one rung lower: note_incident then tracks
            # open pool_bytes_per_device incidents so the pressure
            # gate's pressure_active() probe answers — compaction
            # fires before any shedding tier would
            scheduler.track_pressure = True
        if grammar is not None and scheduler is not None \
                and hasattr(scheduler, "grammar_min_tokens"):
            # arm the degrade floor: a constrained stream is never
            # clamped below its automaton's shortest-accept length —
            # armed only when a consumer exists (the PR-11
            # discipline), so grammar-less schedulers are untouched
            scheduler.grammar_min_tokens = self._grammar_floor
        self.admission = admission or BatchingConfig()
        self._trace_spec = trace
        # ``slo``: None (off — zero monitor work, the default), an
        # obs.slo.SLOMonitor (caller keeps the handle and its
        # IncidentLog), or a sequence of SLO rules (a FRESH monitor is
        # built per run; its incidents land on ServeResult.incidents).
        # The monitor observes the run through MetricsCollector's
        # finish/shed/queue-depth feed — it never touches engine
        # state, so outputs/logs/records are byte-identical either way.
        if slo is not None and not isinstance(
                slo, (obs_slo.SLOMonitor, list, tuple)):
            raise ValueError("slo must be None, an SLOMonitor, or a "
                             "sequence of SLO rules")
        self._slo_spec = slo
        # obs counters prefetched once: the per-event hot path is then
        # one enabled-check + add (the <= 2% tracing-off overhead gate,
        # tools/bench_gate.py obs, prices exactly this)
        _c = obs_metrics.REGISTRY.counter
        self._ctr_arrived = _c("serving_requests_arrived_total",
                               "requests entering the engine")
        self._ctr_tokens = _c("serving_tokens_generated_total",
                              "tokens emitted across all requests")
        self._ctr_shed = _c("serving_requests_shed_total",
                            "requests rejected by the scheduler")
        self._ctr_finished = {
            o: _c("serving_requests_finished_total",
                  "finished requests by outcome", outcome=o)
            for o in ("completed", "cancel", "timeout")}
        self._ctr_compiles = _c("serving_jit_compiles_total",
                                "jit program-cache compiles observed "
                                "by the engine")
        self._ctr_prefix_hits = _c("serving_prefix_hit_tokens_total",
                                   "prompt tokens served from the "
                                   "prefix cache")
        self._ctr_prefix_evictions = _c(
            "serving_prefix_evictions_total",
            "prefix pages reclaimed from the evictable LRU pool")
        self._g_resident = obs_metrics.REGISTRY.gauge(
            "serving_prefix_resident_pages",
            "pool pages held by live sequences")
        self.prefix_cache = bool(prefix_cache)
        # --- async prefill lane (the disaggregation seam) -----------
        # None: legacy interleaved loop — a wave's whole prefill runs
        # at admission, byte-identical to every earlier PR. An int
        # >= 1: admitted requests park in the PREFILL LANE and each
        # engine turn runs the fixed-shape decode batch FIRST, then at
        # most this many prefill chunks — TPOT becomes independent of
        # how much prefill is queued (the DistServe/Splitwise split,
        # in-engine). Requests enter decode slots only when their
        # prefill completes; page/slot accounting and greedy tokens
        # are unchanged (each chunk computes exactly what the
        # monolithic prefill computed for those positions).
        if prefill_chunk_budget is not None and prefill_chunk_budget < 1:
            raise ValueError("prefill_chunk_budget must be >= 1 chunks "
                             "per turn (or None for the interleaved "
                             "legacy loop)")
        self.prefill_chunk_budget = prefill_chunk_budget
        self._g_lane_depth = None
        if prefill_chunk_budget is not None:
            # created ONLY when the lane exists, so pre-disagg runs
            # leave no trace of it in the registry (PR-5 convention)
            self._g_lane_depth = obs_metrics.REGISTRY.gauge(
                "serving_prefill_lane_depth",
                "requests parked in the async prefill lane")
        # --- ragged batched prefill (one program per lane turn) -----
        # False: the lane runs ONE bounded call per request-chunk —
        # byte-identical to every earlier PR. True: each lane turn
        # fuses every parked request's next pending chunk into ONE
        # fixed-shape ragged dispatch (per-row offsets/lengths ride as
        # jit data, so the program cache stays flat across admission
        # mixes); ``prefill_chunk_budget`` then bounds fused DISPATCHES
        # per turn, each advancing the whole lane one chunk. Greedy
        # tokens, page accounting and fixed-clock pricing are unchanged
        # (a fused dispatch of k chunks prices as k chunk calls).
        self.ragged_prefill = bool(ragged_prefill)
        self._p_prefill_ragged = None
        if self.ragged_prefill:
            if prefill_chunk_budget is None:
                raise ValueError(
                    "ragged_prefill=True fuses the async prefill "
                    "lane's pending chunks; pass prefill_chunk_budget "
                    ">= 1 to enable the lane")
            rg = getattr(serving, "prefill_ragged", None)
            if rg is None:
                raise ValueError(
                    "ragged_prefill=True needs a factory that "
                    "advertises prefill_ragged (built with "
                    "chunked_prefill and gather-path prefill "
                    "attention); this factory does not")
            self._p_prefill_ragged = rg
        # --- dispatch-ahead decode turn -----------------------------
        # False: strictly sequential turns (dispatch -> host
        # bookkeeping -> dispatch) — byte-identical to every earlier
        # PR. True: after a decode turn's readback, the NEXT turn's
        # decode batch is dispatched immediately from the post-update
        # slot state, so the device computes while Python routes; the
        # stashed result is served only when the roster fingerprint
        # still matches (any admission/finish/eviction discards it and
        # re-dispatches the identical work). Virtual clocks price the
        # served work exactly as a fresh dispatch, so fixed-clock runs
        # are byte-identical with the flag on; the win is measured
        # wall time.
        self.dispatch_ahead = bool(dispatch_ahead)
        if self.dispatch_ahead and spec is not None:
            raise ValueError(
                "dispatch_ahead=True cannot compose with spec=: "
                "speculative rows decode through a different program "
                "mid-roster, so a dispatched-ahead plain batch would "
                "be stale by construction")
        if self.dispatch_ahead and kv_quant is not None:
            raise ValueError(
                "dispatch_ahead=True cannot compose with kv_quant=: "
                "pressure/int8 tier moves rewrite pool pages between "
                "turns underneath a dispatched-ahead batch")
        if self.dispatch_ahead and grammar is not None:
            raise ValueError(
                "dispatch_ahead=True cannot compose with grammar=: "
                "a constrained row's next mask depends on the token "
                "the CURRENT turn emits, so a dispatched-ahead batch "
                "would mask with a stale DFA state by construction")
        # --- host-DRAM offload arena (inert at hostmem=None) --------
        # None: capacity ends at HBM, byte-identical to every earlier
        # PR (outputs, slot logs, records, report keys, registry).
        # An int byte budget or HostMemConfig arms the THIRD memory
        # tier: pages parked in the evictable LRU spill to a budgeted
        # host arena instead of dying when allocate() recycles them,
        # prefix hits on spilled chains page back in at priced
        # kv_pagein/kv_pageout transfers, and under a QoS scheduler
        # the engine gains the rung between degrade and shed —
        # PREEMPT: swap a low-priority running row's chain out,
        # requeue it with its emitted tokens, swap back in on
        # re-admission.
        self.hostmem = as_hostmem_config(hostmem)
        self._ctr_pageouts = None
        self._ctr_pageins = None
        self._ctr_preempts = None
        self._ctr_restores = None
        if self.hostmem is not None:
            if spec is not None:
                raise ValueError(
                    "hostmem= does not compose with spec= — the "
                    "draft pool rides the target's page ids but "
                    "spills no draft K/V, so a paged-in chain would "
                    "hand the draft a holed cache")
            if self.dispatch_ahead:
                raise ValueError(
                    "hostmem= cannot compose with dispatch_ahead=: "
                    "page-ins and preemption swaps rewrite pool "
                    "pages between turns underneath a "
                    "dispatched-ahead batch")
            # the arena tier is paged-only, exactly like tp: the
            # dense wave cache has no page pool to spill from
            # (self.policy was already built above — rebuild it from
            # the coerced spec)
            policy = _coerce_paged_only(
                policy, "with hostmem",
                "the dense wave cache has no page pool to spill")
            self.policy = make_policy(policy)
            if scheduler is not None \
                    and hasattr(scheduler, "track_preempt"):
                # arm the preempt rung: the scheduler's victim picker
                # answers only when a swap target exists (the PR-11
                # tracked-only-when-armed discipline)
                scheduler.track_preempt = True
            # created ONLY when the arena is configured, so
            # HBM-only runs leave no trace in the registry (PR-5
            # convention)
            _hc = obs_metrics.REGISTRY.counter
            self._ctr_pageouts = _hc(
                "serving_kv_pageouts_total",
                "device pages spilled to the host arena")
            self._ctr_pageins = _hc(
                "serving_kv_pageins_total",
                "host arena pages restored into the device pool")
            self._ctr_preempts = _hc(
                "serving_preemptions_total",
                "running rows swapped out to the host arena by the "
                "QoS preempt rung")
            self._ctr_restores = _hc(
                "serving_preempt_restores_total",
                "preempted rows re-admitted with their chain swapped "
                "back in")
        self.decode_chunk = decode_chunk
        # page-footprint slack beyond prompt+budget: the deepest
        # write a decode turn can land. Plain decode_n writes at most
        # decode_chunk positions past the last emitted token; a spec
        # round's verify block writes n_draft+1 (rejected proposals
        # included — overwritten later, but the pages must exist).
        # spec=None keeps the legacy arithmetic bit-for-bit.
        self._slack = decode_chunk if spec is None \
            else max(decode_chunk, spec.n_draft + 1)
        self.clock_mode = clock
        self.fixed_costs = fixed_costs
        # ``ledger``: None (byte-identical — the tr-is-None
        # convention), True (build a private CostLedger), or a shared
        # obs.ledger.CostLedger (the cluster router passes one so
        # every replica books onto the same accounts). Armed, every
        # priced clock delta and per-turn pool occupancy is
        # attributed (rid | "engine", kind) with exact integer
        # conservation audits; see docs/OBSERVABILITY.md.
        if ledger is True:
            ledger = obs_ledger.CostLedger()
        elif ledger is not None \
                and not isinstance(ledger, obs_ledger.CostLedger):
            raise ValueError("ledger= takes None, True or an "
                             "obs.ledger.CostLedger")
        self._ledger = ledger
        self.eos_token_id = eos_token_id
        self._expect_churn = expect_churn
        self._dense = dense_parts
        (self._p_outer, self._p_layers, pools, self._p_prefill,
         self._p_step, self._p_decode_n) = serving.paged_parts
        # The pool buffers are DONATED through every prefill/decode call,
        # so the factory's original arrays die at the first use. The live
        # pools therefore ride on the (shareable) serving object, not the
        # engine: engines sharing one factory hand the current buffers
        # along. Stale content between runs is harmless — attention only
        # reads positions < each row's length, all freshly written.
        if not hasattr(serving, "_live_pools"):
            serving._live_pools = pools
        # a factory may advertise wants_numpy_ (serving.sim does): its
        # callables take host arrays directly, so the per-call
        # jnp.asarray staging — pure overhead at 10^5-request cluster
        # scale — is skipped; jitted factories keep the conversion.
        # Under tp the staging routes through jax_compat.named_sharding
        # instead of bare jnp.asarray: the plain form commits host
        # batches to the DEFAULT device (the latent single-device
        # assumption), which would force a transfer before every
        # sharded-weight program — replicating onto the mesh up front
        # keeps activations resident where the weights are.
        self._tp_attr = {"tp": tp.size} if tp is not None else {}
        if getattr(serving, "wants_numpy_", False):
            self._arr = lambda x: x
        elif tp is not None:
            # ONE placement: device_put takes the host array straight
            # onto the mesh (a jnp.asarray first would commit it to
            # the default device and pay a second copy per call)
            _rep = named_sharding(tp.build_mesh())
            self._arr = lambda x, _s=_rep: jax.device_put(x, _s)
        else:
            self._arr = jnp.asarray
        # per-device pool residency: measured from the LIVE pool
        # arrays (factories may provide pool_device_bytes — the sim's
        # host pools model the head split arithmetically). Noted on
        # every run's bookkeeper and exported as the
        # serving_pool_bytes_per_device gauge ONLY when sharded
        # (PR-5 nonzero-only convention: tp=None leaves the registry
        # byte-identical).
        self._pool_bytes: Optional[Tuple[int, int]] = None
        self._g_pool_bytes = None
        if tp is not None or kv_quant is not None:
            # a quantizing factory prices its own pool (the sim's
            # token pools model the int8 layout arithmetically; the
            # real factory's leaves ARE the small arrays)
            tfn = getattr(serving, "pool_total_bytes", None)
            total = int(tfn(self._pools)) if tfn is not None \
                else sum(int(getattr(a, "nbytes", 0))
                         for a in jax.tree_util.tree_leaves(self._pools))
            fn = getattr(serving, "pool_device_bytes", None)
            per_dev = int(fn(self._pools)) if fn is not None \
                else tree_device_bytes(self._pools)
            self._pool_bytes = (total, per_dev)
            self._g_pool_bytes = obs_metrics.REGISTRY.gauge(
                "serving_pool_bytes_per_device",
                "KV pool bytes resident on one device of the TP mesh")
            self._g_pool_bytes.set(float(per_dev))

    def pool_bytes_per_device(self) -> Optional[int]:
        """One device's share of the live KV pool, bytes (None when
        the engine is unsharded — the whole pool is one device's)."""
        return self._pool_bytes[1] if self._pool_bytes is not None \
            else None

    def _note_pool(self, book: PagedKVCache, m: MetricsCollector,
                   t: float = 0.0):
        """Stamp the run bookkeeper with the REAL pool's byte census
        and stream the per-device signal to any attached SLO monitor
        (``pool_bytes_per_device`` — a ThresholdRule can watch it).
        No-op unsharded and unquantized: cache_stats/metrics stay
        byte-identical. With kv_quant= the bookkeeper is also armed
        with the tier pricing/compaction hooks here (one seam for
        run(), _run_scheduled() and sessions), and under 'pressure'
        the streamed signal is the LOGICAL stored-byte census —
        occupied pages priced by tier — not the static arena size:
        it moves as rows land and parked pages compact, which is
        exactly what a ThresholdRule needs to watch."""
        if self.kv_quant is not None:
            m.on_kv_quant(self.kv_quant)
            self._arm_quant(book)
        if self._pool_bytes is None:
            return
        book.note_pool_bytes(*self._pool_bytes)
        if self.kv_quant == "pressure":
            sb = book.stored_bytes()
            if sb is not None:
                per = int(sb) // self.tp_size
                m.on_pool_bytes(t, per)
                self._g_pool_bytes.set(float(per))
            return
        m.on_pool_bytes(t, self._pool_bytes[1])

    def _arm_quant(self, book: PagedKVCache):
        """Arm the run bookkeeper's tier census + compaction hooks:
        per-page byte pricing from the factory, the allocation-time
        byte budget, and (pressure) the device-side callback the book
        invokes whenever pages compact — it rebinds the live pools
        through the donating ``compact_pages`` program, so budget-
        driven and incident-driven compaction mutate the device
        arrays through ONE path."""
        pb = getattr(self.serving, "page_bytes_", None)
        cb = None
        if self.kv_quant == "pressure":
            compact = getattr(self.serving, "compact_pages", None)
            if compact is not None:
                wants_np = getattr(self.serving, "wants_numpy_", False)

                def cb(ids, _c=compact, _np=wants_np):
                    mask = np.zeros(self.n_pool_pages, dtype=bool)
                    mask[np.asarray(list(ids), dtype=np.int64)] = True
                    self._pools = _c(self._pools,
                                     mask if _np else jnp.asarray(mask))
        book.note_kv_quant(
            self.kv_quant,
            fp_bytes_per_page=(pb[0] if pb is not None else None),
            q_bytes_per_page=(pb[1] if pb is not None else None),
            byte_budget=self.kv_quant_budget, compact_cb=cb)

    def _make_quant_state(self) -> Optional[dict]:
        """Fresh pressure-actuation state per run/session (tier off,
        empty flip log — two seeded replays flip and compact
        identically), or None unless kv_quant='pressure'."""
        if self.kv_quant != "pressure":
            return None
        return {"enabled": False, "flips": [],
                "compactions": 0, "pages_compacted": 0}

    def _wire_pressure(self, mon, sched):
        """The pressure seam, auto-wired like ``_wire_spec_overload``:
        with kv_quant='pressure', a QoS scheduler and an SLO monitor
        all configured, every incident the monitor opens is delivered
        to ``QoSScheduler.note_incident`` — a
        ``pool_bytes_per_device`` ThresholdRule firing then flips the
        compaction tier until it closes. Idempotent across runs."""
        if mon is None or sched is None or self.kv_quant != "pressure" \
                or not hasattr(sched, "note_incident"):
            return
        if hasattr(sched, "track_pressure"):
            sched.track_pressure = True
        if sched.note_incident not in mon._cbs:
            mon.subscribe(sched.note_incident)

    def _quant_flip(self, qst: dict, m, clock, tr, enabled: bool,
                    rule: str):
        """One deterministic pressure-tier flip on the virtual clock,
        with the rule that fired (the ``explain=`` discipline —
        mirrors ``_spec_flip``)."""
        qst["enabled"] = enabled
        qst["flips"].append({"t": round(clock.now(), 6),
                             "enabled": enabled, "rule": rule})
        m.on_kv_quant_flip(enabled)
        self._ctr_quant_flips["on" if enabled else "off"].inc()
        if tr is not None:
            tr.instant("kv_quant_flip", t=clock.now(), track="engine",
                       enabled=enabled, rule=rule)

    def _quant_turn(self, book: PagedKVCache, m, clock, tr,
                    qst: Optional[dict]):
        """Per-turn pressure bookkeeping, evaluated where the pool
        census is sampled: stream the stored-byte signal, flip the
        tier on the scheduler's open-incident probe, and while it is
        ON compact every page parked in the evictable LRU (capacity
        degradation first — the shedding tiers stay untouched, and a
        page freed by compaction is a request NOT shed). No-op unless
        kv_quant='pressure' (qst is None otherwise)."""
        if qst is None:
            return
        t = clock.now()
        sb = book.stored_bytes()
        if sb is not None:
            per = int(sb) // self.tp_size
            m.on_pool_bytes(t, per)
            if self._g_pool_bytes is not None:
                self._g_pool_bytes.set(float(per))
        sched = self.scheduler
        active = (sched is not None
                  and getattr(sched, "pressure_active", None)
                  is not None and sched.pressure_active())
        if active and not qst["enabled"]:
            self._quant_flip(
                qst, m, clock, tr, True,
                "pool_bytes_per_device incident open via "
                "QoSScheduler.note_incident — compact parked pages "
                "before any shedding tier fires")
        elif not active and qst["enabled"]:
            self._quant_flip(
                qst, m, clock, tr, False,
                "pool-byte incident closed (stored bytes back under "
                "threshold)")
        if qst["enabled"]:
            ids = book.compact_evictable()
            if ids:
                qst["compactions"] += 1
                qst["pages_compacted"] += len(ids)
                m.on_compaction(t, len(ids))
                self._ctr_compactions.inc(len(ids))
                if tr is not None:
                    tr.instant("kv_compaction", t=t, track="engine",
                               pages=len(ids))

    def _quant_result(self, book: PagedKVCache,
                      qst: Optional[dict]) -> Optional[dict]:
        """The ``ServeResult.kv_quant_stats`` block (None at
        kv_quant=None — the pre-quant result shape)."""
        if self.kv_quant is None:
            return None
        cs = book.cache_stats()
        out = {"mode": self.kv_quant,
               "quantized_pages": cs.get("quantized_pages", 0),
               "compactions": cs.get("compactions", 0)}
        sb = book.stored_bytes()
        if sb is not None:
            out["stored_bytes"] = int(sb)
        if qst is not None:
            out["flips"] = list(qst["flips"])
            out["pages_compacted"] = qst["pages_compacted"]
        return out

    def _arm_hostmem(self, book: PagedKVCache, clock, m,
                     tr=None) -> Optional[dict]:
        """Arm the run bookkeeper's host-arena spill tier: a FRESH
        arena per run (two seeded replays spill and page identically),
        the per-page byte prices, and the export closure the book
        invokes whenever an evicted page spills — each crossing is
        priced as one ``kv_pageout`` on the virtual clock (the
        ``adapter_upload``/``KVHandoff`` transfer-pricing pattern).
        Returns the per-run hostmem state dict, or None at
        hostmem=None (every caller then stays byte-identical)."""
        if self.hostmem is None:
            return None
        arena = HostArena(self.hostmem.byte_budget)
        # full-precision per-page price: explicit config override,
        # else the factory's advertisement, else the live pool's
        # measured bytes / page count
        fp = self.hostmem.page_bytes
        if fp is None:
            fp = getattr(self.serving, "page_host_bytes_", None)
        if fp is None:
            pb = getattr(self.serving, "page_bytes_", None)
            fp = pb[0] if pb is not None else None
        if fp is None:
            tfn = getattr(self.serving, "pool_total_bytes", None)
            total = int(tfn(self._pools)) if tfn is not None \
                else sum(int(getattr(a, "nbytes", 0))
                         for a in jax.tree_util.tree_leaves(self._pools))
            fp = max(1, total // max(1, self.n_pool_pages))
        qb = None
        if self.kv_quant is not None:
            # int8 pages spill at their int8+scale price — the
            # kv_quant_page_bytes arithmetic carried across the tier
            pb = getattr(self.serving, "page_bytes_", None)
            qb = pb[1] if pb is not None else None
        hst = {"arena": arena, "fp": int(fp), "qb": qb,
               "preempts": 0, "restores": 0,
               "resume_prefix": {}, "preempted": set()}

        def spill_cb(p, quant):
            data = self._timed(
                tr, clock, "kv_pageout",
                lambda: self.export_kv_pages([p]),
                cost=self._hm_cost("kv_pageout", quant, hst),
                page=p)
            m.on_pageout(clock.now(), 1)
            self._ctr_pageouts.inc()
            return data

        book.note_hostmem(arena, spill_cb, fp, qb)
        return hst

    def _hm_cost(self, kind, quant, hst) -> Optional[float]:
        """Fixed-clock transfer price override for one page crossing:
        an int8 page moves fewer bytes, so it pays the flat
        ``kv_pageout``/``kv_pagein`` cost scaled by its byte ratio.
        None (the clock's own default pricing) on measured clocks and
        for full-precision pages."""
        if self.clock_mode != "fixed" or not quant \
                or hst["qb"] is None or not hst["fp"]:
            return None
        base = (self.fixed_costs or {}).get(kind, 1.0)
        return base * (hst["qb"] / hst["fp"])

    def _pagein_page(self, p, entry, rid, clock, m, tr, hst):
        """The import closure ``PagedKVCache.page_in`` invokes per
        restored page: scatter the arena blob into the device pool at
        page ``p``, priced as one ``kv_pagein``."""
        self._timed(
            tr, clock, "kv_pagein",
            lambda: self.import_kv_pages([p], entry.data),
            rid=rid,
            cost=self._hm_cost("kv_pagein", entry.quant, hst),
            page=p)
        m.on_pagein(clock.now(), 1)
        self._ctr_pageins.inc()

    @staticmethod
    def _stitch_resumes(outputs, hst: Optional[dict]):
        """A preempted request's stream was emitted in two (or more)
        lives: the tokens it streamed before each swap-out, then what
        its resumed run produced. The client saw ONE stream — the
        result reports it as one (a preempted-then-shed request keeps
        the partial stream it was actually served)."""
        if hst is None:
            return
        for rid, pre in hst["resume_prefix"].items():
            outputs[rid] = list(pre) + outputs.get(rid, [])

    def _hostmem_result(self, book: PagedKVCache,
                        hst: Optional[dict]) -> Optional[dict]:
        """The ``ServeResult.hostmem_stats`` block (None at
        hostmem=None — the pre-hostmem result shape)."""
        if hst is None:
            return None
        cs = book.cache_stats()
        return {"arena": hst["arena"].stats(),
                "arena_census_ok": hst["arena"].census_ok(),
                "spilled_pages": cs.get("spilled_pages", 0),
                "spills": cs.get("spills", 0),
                "pageins": cs.get("pageins", 0),
                "spill_refusals": cs.get("spill_refusals", 0),
                "preempts": hst["preempts"],
                "restores": hst["restores"],
                "preempted_rids": sorted(hst["preempted"]
                                         | set(hst["resume_prefix"]))}

    @property
    def _pools(self):
        return self.serving._live_pools

    @_pools.setter
    def _pools(self, value):
        self.serving._live_pools = value

    @property
    def _spec_pools(self):
        return self.serving._live_spec_pools

    @_spec_pools.setter
    def _spec_pools(self, value):
        self.serving._live_spec_pools = value

    # --- tracing helpers --------------------------------------------------
    @staticmethod
    def _tenant_track(r: Request) -> str:
        return f"tenant/{r.tenant}" if r.tenant is not None \
            else "requests"

    def _make_tracer(self, clock) -> Optional[obs_trace.Tracer]:
        spec = self._trace_spec
        if spec is None or spec is False:
            return None
        if isinstance(spec, obs_trace.Tracer):
            t = spec
            t.clear()   # each run() is one trace
        else:
            t = obs_trace.Tracer()
        t.set_clock(clock.now)  # spans live in VIRTUAL time
        return t

    def _close_trace(self, tr: Optional[obs_trace.Tracer]):
        if tr is not None and isinstance(self._trace_spec, str):
            tr.export(self._trace_spec)

    def _make_monitor(self, fresh: bool = True) \
            -> Optional[obs_slo.SLOMonitor]:
        """``fresh``: a caller-held monitor instance is RESET (the
        ``trace=Tracer`` convention — each run() is one monitoring
        session; without the reset a second replay's low virtual
        timestamps would be instantly outside the first run's
        advanced windows and every rule would go blind). Sessions
        pass ``fresh=False`` — they are incremental by design and a
        reset would nuke a log shared with sibling sessions."""
        spec = self._slo_spec
        if spec is None:
            return None
        if isinstance(spec, obs_slo.SLOMonitor):
            if fresh:
                spec.reset()
            return spec
        return obs_slo.SLOMonitor(spec)

    def _make_adapter_cache(self) -> Optional[AdapterCache]:
        """A FRESH adapter cache per run/session (cold bank — two
        seeded replays upload identically), or None when the engine is
        single-model. The device hooks come from the factory
        (``init_adapter_bank``/``upload_adapter``); the bank is sized
        by the factory's ``lora_.n_slots``."""
        if self._adapter_store is None:
            return None
        return AdapterCache(self._adapter_store, self.lora.n_slots,
                            self.serving.init_adapter_bank,
                            self.serving.upload_adapter)

    def _make_grammar_cache(self) -> Optional[GrammarCache]:
        """A FRESH grammar cache per run/session (cold mask bank —
        two seeded replays upload identically), or None when the
        engine is free-running. The device hooks come from the
        factory (``init_grammar_bank``/``upload_grammar``); the bank
        is sized by the factory's ``grammar_`` config. The HOST
        compile memo is the engine's (shared across runs/sessions and
        with the scheduler's floor probe): a schema's automaton
        compiles once per engine, only the bank upload repeats."""
        if self._grammar_store is None:
            return None
        gc = GrammarCache(
            self._grammar_store, self.grammar_cfg.n_slots,
            self.grammar_cfg.max_states,
            TokenVocab.ascii_default(self.serving.grammar_vocab_),
            self.serving.init_grammar_bank,
            self.serving.upload_grammar)
        gc._dfa = self._dfa_memo
        return gc

    def _grammar_arg(self, gcache: Optional[GrammarCache], gids):
        """The ``grammar=`` argument for a factory call:
        ``(mask_table, state_ids)`` when constrained decoding is on
        (flat ids staged like every other host batch input), None
        otherwise — free-running engines call the factory EXACTLY as
        before, so their programs and outputs are untouched."""
        if gcache is None:
            return None
        return (gcache.bank, self._arr(np.asarray(gids, np.int32)))

    def _schema_of(self, r: Request) -> Optional[str]:
        """The schema this request decodes under: its own
        ``Request.schema`` first, else its adapter's default from
        ``adapter_schemas=``, else None (free-running). Always None
        on a grammar-less engine — ``_validate`` already refused any
        request that NAMES a schema there."""
        if self._grammar_store is None:
            return None
        if r.schema is not None:
            return r.schema
        if r.adapter is not None:
            return self._adapter_schemas.get(r.adapter)
        return None

    def _grammar_automaton(self, name: str):
        """Compile-and-memoize ``name``'s automaton host-side (the
        engine-lifetime memo every run's GrammarCache shares). No
        bank slot is touched — this is the probe path."""
        g = self._dfa_memo.get(name)
        if g is None:
            from .grammar import compile_source
            g = compile_source(self._grammar_store.get(name),
                               TokenVocab.ascii_default(
                                   self.serving.grammar_vocab_))
            if g.n_states > self.grammar_cfg.max_states:
                raise ValueError(
                    f"grammar {name!r} compiles to {g.n_states} "
                    f"states but the bank holds max_states="
                    f"{self.grammar_cfg.max_states}")
            self._dfa_memo[name] = g
        return g

    def _grammar_floor(self, r: Request) -> Optional[int]:
        """The scheduler's degrade floor for one request: the
        shortest token count its automaton accepts (None for free
        rows — the legacy floor of 1 applies)."""
        name = self._schema_of(r)
        if name is None:
            return None
        return int(self._grammar_automaton(name).min_tokens)

    def _make_spec_state(self) -> Optional[_SpecState]:
        """Fresh adaptive-route state per run/session (cold EWMA,
        empty flip log — two seeded replays flip identically), or
        None when the engine is spec-free."""
        if self.spec is None:
            return None
        return _SpecState(self.spec)

    def _make_ahead_state(self) -> Optional[_AheadState]:
        """Fresh dispatch-ahead double buffer per run/session (no
        stash can ever cross runs), or None with the flag off — every
        pass-through then sees exactly the legacy sequential turn."""
        return _AheadState() if self.dispatch_ahead else None

    def _wire_spec_overload(self, mon, sched):
        """The declared overload seam, auto-wired: with a spec route,
        a QoS scheduler and an SLO monitor all configured, every
        incident the monitor opens is delivered to
        ``QoSScheduler.note_incident`` — a page-severity
        ``BurnRateRule`` firing then parks the spec route until it
        closes. Idempotent: a caller-held monitor reused across runs
        never double-subscribes."""
        if mon is None or sched is None or self.spec is None \
                or not self.spec.overload_fallback \
                or not hasattr(sched, "note_incident"):
            return
        if sched.note_incident not in mon._cbs:
            mon.subscribe(sched.note_incident)

    def _spec_flip(self, spst: _SpecState, clock, tr, enabled: bool,
                   rule: str):
        """One deterministic route flip on the virtual clock, with
        the rule that fired (the ``explain=`` discipline)."""
        spst.enabled = enabled
        flip = {"t": round(clock.now(), 6), "enabled": enabled,
                "rule": rule}
        spst.flips.append(flip)
        self._ctr_spec_flips["spec" if enabled else "plain"].inc()
        if tr is not None:
            tr.instant("spec_flip", t=clock.now(), track="engine",
                       enabled=enabled, rule=rule)

    def _spec_gate(self, spst: _SpecState, clock, tr):
        """Evaluate the adaptive fallbacks once per decode turn,
        BEFORE the rows are grouped: overload first (spec wastes
        draft compute exactly when capacity is scarce — the moment a
        page-severity incident lands through
        ``QoSScheduler.note_incident``, spec rows decode plain until
        it closes), then the acceptance floor (EWMA below
        ``accept_floor`` after ``min_rounds`` row-rounds LATCHES
        plain for the rest of the run — with no spec rounds running,
        no new evidence could clear it)."""
        cfg = spst.cfg
        if spst.latched:
            return
        if cfg.overload_fallback and self.scheduler is not None \
                and getattr(self.scheduler, "overload_active",
                            None) is not None \
                and self.scheduler.overload_active():
            if spst.enabled:
                self._spec_flip(
                    spst, clock, tr, False,
                    "overload (page-severity incident open via "
                    "QoSScheduler.note_incident — draft compute is "
                    "waste when capacity is scarce)")
            return
        if spst.ewma is not None and spst.samples >= cfg.min_rounds \
                and spst.ewma < cfg.accept_floor:
            spst.latched = True
            if spst.enabled:
                self._spec_flip(
                    spst, clock, tr, False,
                    f"acceptance ewma {spst.ewma:.4f} < floor "
                    f"{cfg.accept_floor} after {spst.samples} spec "
                    "turns (latched plain for the run)")
            return
        if not spst.enabled:
            self._spec_flip(spst, clock, tr, True,
                            "overload cleared (incident closed)")

    def _spec_prefill_row(self, r: Request, book, T: int, clock, tr):
        """DRAFT prefill for one spec-eligible row, at the moment its
        target prompt pages hold real K/V: the draft walks the FULL
        prompt through the SAME page chain into its own pool arrays.
        Unlike the target, the draft never takes the prefix-cache
        skip — a cached chain's publisher may have been plain-routed
        (tight traffic, a latched run, ``prefix_cache`` off), in
        which case its draft pages were never written, and a draft
        conditioned on junk would quietly collapse acceptance. The
        walk is cheap by construction (the draft is a fraction of
        the target); the expensive TARGET prefill still takes the
        full cache skip. Clock kind ``spec_prefill`` (per-unit via
        ``spec_prefill_unit`` when the cost table carries it)."""
        sid = r.rid
        toks = np.zeros((1, T), np.int32)
        toks[0, :len(r.prompt)] = r.prompt
        pt = np.zeros((1, self.W), np.int32)
        table = book.tables[sid]
        pt[0, :len(table)] = table
        lens = np.asarray([len(r.prompt)], np.int32)
        s_outer, s_layers, _, s_prefill, _ = self._spec_parts

        def _call():
            arr = self._arr
            return s_prefill(s_outer, s_layers, arr(toks), arr(pt),
                             arr(lens), self._spec_pools,
                             resume_from=0)
        _, self._spec_pools = self._timed(
            tr, clock, "spec_prefill", _call, jitfn=s_prefill,
            rid=sid, units=T // self.chunk_C, **self._tp_attr)

    def _lora_arg(self, acache: Optional[AdapterCache], ids):
        """The ``lora=`` argument for a factory call: ``(bank, ids)``
        when multi-model serving is on (ids staged like every other
        host batch input), None otherwise — single-model engines call
        the factory EXACTLY as before, so their programs and outputs
        are untouched."""
        if acache is None:
            return None
        return (acache.bank, self._arr(np.asarray(ids, np.int32)))

    def _note_adapters(self, acache: Optional[AdapterCache], m, t):
        """Refresh the resident-adapter gauge and stream the count to
        any attached SLO monitor. No-op single-model."""
        if acache is None:
            return
        n = acache.resident_count()
        self._g_adapter_resident.set(float(n))
        m.on_adapter_resident(t, n)

    @staticmethod
    def _bank_incidents(mon) -> Optional[List]:
        """This run's incidents for ServeResult: the monitor's view of
        its own source (a cluster replica shares one IncidentLog with
        its siblings — its per-replica result banks only what IT
        fired; the router's ClusterResult carries the full set)."""
        if mon is None:
            return None
        return [i for i in mon.log.incidents if i.source == mon.source]

    def _make_clock(self, label: str = "engine") -> EngineClock:
        """This run's virtual clock: plain (byte-identical) without a
        ledger, ledger-booking with one — ``label`` names the
        per-engine conservation book (the replica name in cluster
        runs)."""
        if self._ledger is None:
            return EngineClock(self.clock_mode, self.fixed_costs)
        return _LedgerClock(self.clock_mode, self.fixed_costs,
                            self._ledger, label)

    def _req_features(self, r: Request) -> Tuple[str, ...]:
        """The request's static feature tags for the ledger's
        per-feature rollup (engine-wide transforms plus the request's
        own asks); dynamic ones (spec/hostmem/ragged) derive from the
        kinds actually charged."""
        feats = []
        if getattr(self, "tp_size", 1) > 1:
            feats.append("tp")
        if self.kv_quant is not None:
            feats.append("kv_quant")
        if r.adapter is not None:
            feats.append("lora")
        if self._schema_of(r) is not None:
            feats.append("grammar")
        return tuple(feats)

    def _req_open(self, tr, r: Request):
        if self._ledger is not None:
            self._ledger.open(r.rid, tenant=r.tenant,
                              features=self._req_features(r))
        if tr is None:
            return
        attrs = {"prompt_len": len(r.prompt),
                 "budget": r.max_new_tokens}
        if r.tenant is not None:
            attrs["tenant"] = r.tenant
        if r.priority:
            attrs["priority"] = r.priority
        if r.deadline_ms is not None:
            attrs["deadline_ms"] = r.deadline_ms
        tr.async_begin("request", r.rid, t=r.arrival,
                       track=self._tenant_track(r), **attrs)

    def _req_close(self, tr, r: Request, t: float, outcome: str,
                   n_tokens: int, reason: Optional[str] = None):
        if self._ledger is not None:
            # moves ("failover"/"handoff"/"requeued") and the final
            # outcome collect IN ORDER on the one shared account —
            # the exactly-once evidence chaos accounting asserts on
            self._ledger.note_outcome(r.rid, outcome)
        if tr is None:
            return
        attrs = {"outcome": outcome, "n_tokens": n_tokens}
        if reason is not None:
            attrs["reason"] = reason
        tr.async_end("request", r.rid, t=t,
                     track=self._tenant_track(r), **attrs)

    def _wave_instant(self, tr, decision: dict):
        if tr is not None:
            tr.instant("wave", t=decision["t"], track="engine",
                       **{k: v for k, v in decision.items()
                          if k != "t"})

    def _timed(self, tr, clock, kind, fn, jitfn=None, rid=None,
               units=None, cost=None, rids=None, **attrs):
        """``clock.timed`` plus, when tracing, a span in virtual time
        (wall seconds as an attr) and jit-recompile detection: the
        wrapped program cache growing across the call means THIS call
        compiled — the ``jit.compile`` instant names the site and the
        wall cost, the counter feeds the metrics registry.

        ``rids`` (batched dispatches) is the cost ledger's attribution
        vector: the charge splits pro-rata across the rows — by the
        per-row ``cost`` list when the call priced one (the ragged
        fused convention), equally otherwise. With ``rids`` unset the
        charge lands on ``rid``, or on "engine" when the call has no
        single beneficiary. Every priced call site funnels through
        here, so a ledger-armed run can never book an unattributed
        unit (the audit enforces it)."""
        setter = getattr(clock, "push_attr", None)
        if setter is not None:
            setter(rid, rids,
                   cost if isinstance(cost, (list, tuple)) else None)
        if tr is None:
            # no trace: recompile COUNTING stays live (the obs
            # contract — counters record when nobody traces) unless
            # the registry kill-switch is down (the no-obs arm);
            # detection is two cache-size reads around the call
            if jitfn is None or not obs_metrics.REGISTRY.enabled:
                return clock.timed(kind, fn, units, cost)
            c0 = _jit_cache_size(jitfn)
            out = clock.timed(kind, fn, units, cost)
            if c0 is not None:
                c1 = _jit_cache_size(jitfn)
                if c1 is not None and c1 > c0:
                    self._ctr_compiles.inc()
            return out
        t0 = clock.now()
        w0 = time.perf_counter()
        c0 = _jit_cache_size(jitfn) if jitfn is not None else None
        scope = obs_trace.trace_scope(rid) if rid is not None else None
        if scope is not None:
            with scope:
                out = clock.timed(kind, fn, units, cost)
        else:
            out = clock.timed(kind, fn, units, cost)
        wall = time.perf_counter() - w0
        if rid is not None:
            attrs["rid"] = rid
        tr.add_span(kind, t0, clock.now() - t0, track="engine",
                    wall_s=round(wall, 6), **attrs)
        if c0 is not None:
            c1 = _jit_cache_size(jitfn)
            if c1 is not None and c1 > c0:
                self._ctr_compiles.inc()
                inst = {"site": kind, "wall_s": round(wall, 6)}
                if rid is not None:
                    inst["rid"] = rid
                tr.instant("jit.compile", t=t0, track="jit", **inst)
        return out

    # --- helpers ----------------------------------------------------------
    def _pad_len(self, n: int) -> int:
        # pad prompts to the CHUNK multiple (a page multiple by factory
        # contract): prefill_chunked rejects prompts that are not — a
        # page-size pad under a larger chunk would crash mid-run
        c = self.chunk_C
        return max(c, -(-n // c) * c)

    def _footprint_len(self, prompt_len: int, budget: int) -> int:
        """The one footprint formula (`_validate` enforces it against
        ``max_len``; the cluster's retry sizing asks it before growing
        a resumed prompt): padded prompt + decode budget + one turn of
        write slack (a decode chunk, or the spec verify window when a
        spec route is configured — whichever writes deeper)."""
        return self._pad_len(prompt_len) + budget + self._slack

    def _footprint(self, r: Request) -> int:
        return self._footprint_len(len(r.prompt), r.max_new_tokens)

    def _order_wave(self, wave) -> List[Request]:
        """Cache-aware co-scheduling for the FIFO loop's PAGED branch:
        requests whose prompts open with the same first page become
        ADJACENT (groups in first-arrival order, members in their
        incoming order), so when slots run out mid-wave a cohort is
        admitted together — its publisher registers before the
        siblings prefill (register-then-acquire) and the shared pages
        stay resident while every sharer needs them. Prompts that
        share no page keep their order exactly (every group is a
        singleton), so plain traces replay bit-identically. Routing,
        dense waves and the QoS loop never see this reordering: dense
        has no page cache to win, and the QoS scheduler's
        priority/WFQ order is authoritative (cache awareness enters
        its admission through ``ServiceEstimator.prefill_cost``
        pricing instead, so adjacency can never invert a priority
        decision)."""
        if not self.prefix_cache or len(wave) < 2:
            return list(wave)
        ps = self.page_size
        groups: Dict = {}
        order: List = []
        for i, r in enumerate(wave):
            # adapter id joins the grouping key: rows of one adapter
            # become ADJACENT segments of the admission wave (the
            # segment-gather layout the batched delta application
            # reads), and a cohort sharing both prefix and adapter
            # still co-schedules. Adapter-less traces key every row
            # with the same None, so their ordering is untouched.
            key = (r.adapter, tuple(r.prompt[:ps])) \
                if len(r.prompt) >= ps else (r.adapter, ("short", i))
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append(r)
        return [r for k in order for r in groups[k]]

    def _validate(self, trace):
        for r in trace:
            if self._footprint(r) > self.max_len:
                raise ValueError(
                    f"{r.rid}: padded prompt {self._pad_len(len(r.prompt))}"
                    f" + budget {r.max_new_tokens} + write slack "
                    f"{self._slack} exceeds max_len {self.max_len}")
            if r.adapter is not None:
                if self._adapter_store is None:
                    raise ValueError(
                        f"{r.rid}: names adapter {r.adapter!r} but "
                        "the engine was built without adapters= — a "
                        "silent base-model answer would be the wrong "
                        "model's tokens")
                if r.adapter not in self._adapter_store:
                    raise ValueError(
                        f"{r.rid}: unknown adapter {r.adapter!r} "
                        f"(registered: {self._adapter_store.names()})")
            if r.schema is not None:
                if self._grammar_store is None:
                    raise ValueError(
                        f"{r.rid}: names schema {r.schema!r} but the "
                        "engine was built without grammar= — a "
                        "free-running answer would break the "
                        "declared output contract")
                if r.schema not in self._grammar_store:
                    raise ValueError(
                        f"{r.rid}: unknown schema {r.schema!r} "
                        f"(registered: {self._grammar_store.names()})")

    # --- the replay loop --------------------------------------------------
    def run(self, trace: List[Request]) -> ServeResult:
        if self.scheduler is not None:
            return self._run_scheduled(trace, self.scheduler)
        self._validate(trace)
        clock = self._make_clock()
        tr = self._make_tracer(clock)
        mon = self._make_monitor()
        m = MetricsCollector(monitor=mon)
        book = PagedKVCache(self.n_pool_pages, self.page_size,
                            kv_heads=1, head_dim=1)  # bookkeeping only:
        # tables/lengths/free-list/prefix refcounts — device pages live
        # in the factory pools, written by prefill/decode_n
        self._note_pool(book, m)
        hst = self._arm_hostmem(book, clock, m, tr)
        acache = self._make_adapter_cache()
        gcache = self._make_grammar_cache()
        spst = self._make_spec_state()
        qst = self._make_quant_state()
        ahst = self._make_ahead_state()
        run_w0 = time.perf_counter()
        pages_total = len(book._free)
        pending = deque(sorted(trace, key=lambda r: (r.arrival, r.rid)))
        waiting: List[Request] = []
        active: Dict[str, _PagedRow] = {}
        lane = deque() if self.prefill_chunk_budget is not None \
            else None
        free_slots = list(range(self.slots))
        outputs: Dict[str, List[int]] = {}
        decisions: List[dict] = []
        slot_log: List[tuple] = []
        prefix_cached: Dict[str, int] = {}
        seen_groups: set = set()
        prefill_tokens = 0
        inv_ok = True
        a_inv = True
        g_inv = True
        expect_churn = self._expect_churn if self._expect_churn \
            is not None else any(r.cancel_after is not None
                                 for r in trace)
        ctx_base = {"capacity": self.slots, "expect_churn": expect_churn}

        prev_tr = obs_trace.active()
        if tr is not None:
            obs_trace.activate(tr)
        try:
            while pending or waiting or active or lane:
                now = clock.now()
                while pending and pending[0].arrival <= now + 1e-12:
                    r = pending.popleft()
                    waiting.append(r)
                    # QoS fields ride along so a FIFO baseline run on a
                    # QoS trace still reports deadline attainment/goodput;
                    # on a plain trace they are all None and the metrics
                    # record stays byte-identical to PR 2
                    m.on_arrival(r.rid, r.arrival, tenant=r.tenant,
                                 priority=r.priority,
                                 deadline_ms=r.deadline_ms)
                    self._ctr_arrived.inc()
                    self._req_open(tr, r)
                m.on_queue_depth(now, len(waiting))
                if tr is not None:
                    tr.counter("queue_depth", len(waiting), t=now)

                progressed = False
                if waiting and self._admission_ready(waiting, pending,
                                                     active, clock):
                    wave = waiting[:self.admission.max_batch]
                    groups = [r.prefix_group for r in wave
                              if r.prefix_group is not None]
                    shared = (len(groups) != len(set(groups))
                              or any(g in seen_groups for g in groups))
                    ctx = dict(ctx_base, shared_prefix=shared,
                               active_paged=len(active)
                               + (len(lane) if lane else 0))
                    backend, reason = self.policy.route(wave, ctx)
                    decision = {
                        "t": round(clock.now(), 6), "wave": len(wave),
                        "prompt_lens": [len(r.prompt) for r in wave],
                        "backend": backend, "rule": reason}
                    if backend == "dense":
                        decisions.append(decision)
                        self._wave_instant(tr, decision)
                        del waiting[:len(wave)]
                        seen_groups.update(g for g in groups)
                        self._run_dense_wave(wave, clock, m, outputs,
                                             tr=tr)
                        progressed = True
                    else:
                        # only the paged ADMISSION order is cache-
                        # reordered (routing and the decision log keep
                        # arrival order)
                        wave = self._order_wave(wave)
                        n_adm, _, ptoks = self._admit_paged(
                            wave, book, clock, m, active, free_slots,
                            slot_log, prefix_cached, seen_groups,
                            outputs, tr=tr, lane=lane, acache=acache,
                            spst=spst, hst=hst, gcache=gcache)
                        prefill_tokens += ptoks
                        for r in wave[:n_adm]:  # possibly reordered —
                            waiting.remove(r)   # remove by identity
                        progressed = n_adm > 0
                        if n_adm:
                            # a BLOCKED wave (no slots/pages yet) is not a
                            # decision — it will re-route once something
                            # frees; logging every retry turn would inflate
                            # the per-wave statistics the bench reports
                            decision["admitted"] = n_adm
                            # prompt_lens above is ARRIVAL order; the
                            # cache reorder means the first-n slice no
                            # longer names the admitted set — the rids do
                            decision["admit_rids"] = \
                                [r.rid for r in wave[:n_adm]]
                            decisions.append(decision)
                            self._wave_instant(tr, decision)
                        elif not active and not lane:
                            raise RuntimeError(
                                f"pool/slot config too small for "
                                f"{wave[0].rid} (free pages "
                                f"{len(book._free)}, free slots "
                                f"{len(free_slots)})")

                if active:
                    self._paged_chunk(book, clock, m, active, free_slots,
                                      slot_log, outputs, tr=tr,
                                      acache=acache, spst=spst,
                                      ahst=ahst, gcache=gcache)
                    progressed = True

                if lane:
                    # the async lane: decode ran FIRST — pending
                    # prefill gets at most prefill_chunk_budget chunks
                    # of this turn, so TPOT is independent of how much
                    # prefill is queued
                    _, ptoks = self._lane_step(
                        lane, book, clock, m, active, free_slots,
                        slot_log, outputs, prefix_cached, seen_groups,
                        tr=tr, acache=acache, spst=spst,
                        gcache=gcache)
                    prefill_tokens += ptoks
                    progressed = True

                if not progressed and not active:
                    targets = []
                    if pending:
                        targets.append(pending[0].arrival)
                    if waiting:
                        targets.append(waiting[0].arrival
                                       + self.admission.max_delay)
                    clock.advance_to(min(targets))
                self._quant_turn(book, m, clock, tr, qst)
                inv_ok &= book.census_ok()
                if acache is not None:
                    a_inv &= acache.census_ok()
                if gcache is not None:
                    g_inv &= gcache.census_ok()
                if self._ledger is not None:
                    self._ledger.sample_occupancy(
                        clock.label, book=book, acache=acache,
                        gcache=gcache,
                        arena=getattr(book, "_arena", None))
        finally:
            if tr is not None:
                if prev_tr is not None:
                    obs_trace.activate(prev_tr)
                else:
                    obs_trace.deactivate()
        cost_stats = self._cost_result(clock, tr, m)
        self._close_trace(tr)
        self._stitch_resumes(outputs, hst)
        return ServeResult(policy=self.policy.name, outputs=outputs,
                           metrics=m, decisions=decisions,
                           slot_log=slot_log, prefix_cached=prefix_cached,
                           pages_total=pages_total,
                           pages_free_end=(len(book._free)
                                           + len(book._evictable)),
                           trace=tr, prefill_tokens=prefill_tokens,
                           cache_stats=dict(book.cache_stats(),
                                            invariant_ok=inv_ok),
                           incidents=self._bank_incidents(mon),
                           adapter_stats=(
                               None if acache is None else
                               dict(acache.cache_stats(),
                                    invariant_ok=a_inv)),
                           spec_stats=(None if spst is None
                                       else spst.stats()),
                           kv_quant_stats=self._quant_result(book,
                                                             qst),
                           overhead=self._overhead_row(clock, run_w0),
                           hostmem_stats=self._hostmem_result(book,
                                                              hst),
                           pages_spilled=(
                               None if hst is None else
                               book.cache_stats().get(
                                   "spilled_pages", 0)),
                           grammar_stats=(
                               None if gcache is None else
                               dict(gcache.cache_stats(),
                                    invariant_ok=g_inv)),
                           cost_stats=cost_stats)

    def _overhead_row(self, clock, run_w0) -> Optional[Dict]:
        """The measured-clock host-overhead decomposition:
        ``engine_host_frac`` is the fraction of the run's wall time
        NOT covered by in-flight device work (timed dispatch waits,
        plus the overlapped span of every dispatched-ahead batch that
        was served). Dispatch-ahead exists to shrink it. None on
        fixed clocks — their results stay byte-identical."""
        if self.clock_mode != "measured":
            return None
        run_wall = time.perf_counter() - run_w0
        dev = min(clock.dev_wall, run_wall)
        frac = 1.0 - dev / run_wall if run_wall > 0 else 0.0
        return {"run_wall_s": round(run_wall, 6),
                "device_wall_s": round(dev, 6),
                "engine_host_frac": round(max(0.0, frac), 6)}

    def _cost_result(self, clock, tr=None, m=None) -> Optional[Dict]:
        """Bank the cost ledger's run-end evidence for this engine's
        book: ``cost_stats`` (unit totals, per-kind breakdown, the
        page-turn integral, and both conservation-audit flags), one
        ``cost`` instant on the trace's engine track (armed AND
        tracing only — un-armed traces stay byte-identical), and the
        watermarked Prometheus publish (safe to repeat on a shared
        cluster ledger). None when the run carries no ledger, so the
        result shape every pre-ledger consumer sees is unchanged."""
        if self._ledger is None:
            return None
        label = getattr(clock, "label", "engine")
        stats = self._ledger.cost_stats(label)
        if m is not None:
            m.note_costs(self._ledger.tenant_costs())
        if tr is not None:
            tr.instant("cost", t=clock.now(), track="engine",
                       **{k: stats[k] for k in
                          ("engine", "elapsed_units", "idle_units",
                           "attributed_units", "page_turns",
                           "conserved_ok", "occupancy_ok")})
        self._ledger.publish(obs_metrics.REGISTRY)
        return stats

    def _admission_ready(self, waiting, pending, active, clock) -> bool:
        if len(waiting) >= self.admission.max_batch:
            return True
        # the window-close test MUST round identically to the idle
        # target `arrival + max_delay` the loop advances to: comparing
        # `now - arrival >= max_delay` instead livelocks once the
        # clock is large enough that one ulp exceeds the epsilon
        # (advance_to(target) lands ON target yet reads as not-ready
        # — first seen at t ~ 6e4 on the 10^5-request cluster trace)
        if clock.now() >= waiting[0].arrival \
                + self.admission.max_delay - 1e-12:
            return True
        return not pending and not active  # nothing else will ever come

    # --- the QoS-scheduled replay loop ------------------------------------
    def _run_scheduled(self, trace: List[Request],
                       sched) -> ServeResult:
        """The same arrive->admit->route->prefill->decode lifecycle,
        with the scheduler owning the waiting set: it orders admission
        (priority above weighted fair queueing), sheds what cannot meet
        its deadline (at enqueue under a queue bound, at selection once
        infeasible), clamps budgets through degradation tiers, and the
        engine times out RUNNING rows past their deadline through the
        same eviction path ``cancel_after`` uses."""
        self._validate(trace)
        sched.reset()
        clock = self._make_clock()
        tr = self._make_tracer(clock)
        costs = self.fixed_costs or {}
        est_kw = {}
        if "prefill_unit" in costs:
            # per-chunk clock pricing -> per-chunk admission pricing
            # (the feasibility check then sees exactly what the clock
            # will charge, cached chunks excluded)
            est_kw = {"prefill_unit": costs["prefill_unit"],
                      "chunk_tokens": self.chunk_C}
        est = ServiceEstimator(prefill=costs.get("prefill", 1.0),
                               decode=costs.get("decode", 1.0),
                               **est_kw)
        mon = self._make_monitor()
        self._wire_spec_overload(mon, sched)
        self._wire_pressure(mon, sched)
        m = MetricsCollector(monitor=mon)
        book = PagedKVCache(self.n_pool_pages, self.page_size,
                            kv_heads=1, head_dim=1)
        self._note_pool(book, m)
        hst = self._arm_hostmem(book, clock, m, tr)
        acache = self._make_adapter_cache()
        gcache = self._make_grammar_cache()
        spst = self._make_spec_state()
        qst = self._make_quant_state()
        ahst = self._make_ahead_state()
        run_w0 = time.perf_counter()
        pages_total = len(book._free)
        pending = deque(sorted(trace, key=lambda r: (r.arrival, r.rid)))
        active: Dict[str, _PagedRow] = {}
        lane = deque() if self.prefill_chunk_budget is not None \
            else None
        free_slots = list(range(self.slots))
        outputs: Dict[str, List[int]] = {}
        decisions: List[dict] = []
        slot_log: List[tuple] = []
        prefix_cached: Dict[str, int] = {}
        shed_log: Dict[str, str] = {}
        seen_groups: set = set()
        prefill_tokens = 0
        inv_ok = True
        a_inv = True
        g_inv = True
        expect_churn = self._expect_churn if self._expect_churn \
            is not None else any(r.cancel_after is not None
                                 for r in trace)
        ctx_base = {"capacity": self.slots, "expect_churn": expect_churn}

        def _shed(pairs):
            for r, reason in pairs:
                t = clock.now()
                m.on_shed(r.rid, t, reason)
                shed_log[r.rid] = reason
                self._ctr_shed.inc()
                if acache is not None:
                    acache.forget_pending(r.rid)
                if gcache is not None:
                    gcache.forget_pending(r.rid)
                if hst is not None and r.rid in hst["preempted"]:
                    # a preempted request shed while requeued: its
                    # pinned chain will never page back in — release
                    # the arena bytes (the partial stream it was
                    # served survives via _stitch_resumes)
                    hst["preempted"].discard(r.rid)
                    book.drop_spilled_owner(r.rid)
                if tr is not None:
                    tr.instant("shed", t=t, track="scheduler",
                               rid=r.rid, reason=reason,
                               tenant=r.tenant)
                self._req_close(tr, r, t, "shed", 0, reason=reason)
            return bool(pairs)

        prev_tr = obs_trace.active()
        if tr is not None:
            obs_trace.activate(tr)
        try:
            while pending or sched.waiting() or active or lane:
                now = clock.now()
                while pending and pending[0].arrival <= now + 1e-12:
                    r = pending.popleft()
                    m.on_arrival(r.rid, r.arrival, tenant=r.tenant,
                                 priority=r.priority,
                                 deadline_ms=r.deadline_ms)
                    self._ctr_arrived.inc()
                    self._req_open(tr, r)
                    _shed(sched.enqueue(r, now))
                m.on_queue_depth(now, sched.waiting())
                if tr is not None:
                    tr.counter("queue_depth", sched.waiting(), t=now)
                progressed = _shed(sched.shed_expired(now))

                if sched.waiting() and self._sched_ready(sched, pending,
                                                         active, clock):
                    dec = sched.select(now,
                                       max_batch=self.admission.max_batch,
                                       est=est,
                                       decode_chunk=self.decode_chunk,
                                       match_prefix=(book.match_prefix
                                                     if self.prefix_cache
                                                     else None),
                                       backlog_cost=(
                                           self._lane_backlog_cost(
                                               lane, est)
                                           if lane else 0.0))
                    progressed |= _shed(dec.shed)
                    # the scheduler's priority/WFQ order is kept as-is:
                    # its feasibility estimates assumed it, and a cache
                    # reorder could hand a scarce slot to a lower class
                    # (cache awareness is in the select() pricing)
                    wave = dec.wave
                    if wave:
                        groups = [r.prefix_group for r in wave
                                  if r.prefix_group is not None]
                        shared = (len(groups) != len(set(groups))
                                  or any(g in seen_groups
                                         for g in groups))
                        ctx = dict(ctx_base, shared_prefix=shared,
                                   active_paged=len(active)
                                   + (len(lane) if lane else 0))
                        backend, reason = self.policy.route(wave, ctx)
                        decision = {
                            "t": round(clock.now(), 6), "wave": len(wave),
                            "prompt_lens": [len(r.prompt) for r in wave],
                            "backend": backend, "rule": reason,
                            "rids": [r.rid for r in wave]}
                        if backend == "dense":
                            decisions.append(decision)
                            self._wave_instant(tr, decision)
                            seen_groups.update(g for g in groups)
                            self._commit_wave(wave, dec, sched, m,
                                              tr=tr, t=clock.now())
                            self._run_dense_wave(wave, clock, m, outputs,
                                                 timeouts=True, tr=tr)
                            progressed = True
                        else:
                            t0 = clock.now()
                            n_adm, n_chunks, ptoks = self._admit_paged(
                                wave, book, clock, m, active, free_slots,
                                slot_log, prefix_cached, seen_groups,
                                outputs, tr=tr, lane=lane,
                                acache=acache, spst=spst, hst=hst,
                                gcache=gcache)
                            prefill_tokens += ptoks
                            if n_adm:
                                dt = clock.now() - t0
                                est.observe("prefill", dt / n_adm)
                                if n_chunks and "prefill_unit" \
                                        in est.costs:
                                    est.observe("prefill_unit",
                                                dt / n_chunks)
                                self._commit_wave(wave[:n_adm], dec,
                                                  sched, m, tr=tr,
                                                  t=clock.now())
                                decision["admitted"] = n_adm
                                decisions.append(decision)
                                self._wave_instant(tr, decision)
                                progressed = True
                            elif hst is not None and active \
                                    and self._preempt_turn(
                                        wave[0], book, clock, m,
                                        active, free_slots, slot_log,
                                        sched, hst, _shed, tr=tr,
                                        acache=acache, gcache=gcache):
                                # the rung between degrade and shed:
                                # a fully blocked wave swaps ONE
                                # lower-priority running row out to
                                # the arena; the blocked request
                                # stays queued and admits next turn
                                # into the freed slot/pages
                                progressed = True
                            elif not active and not lane:
                                raise RuntimeError(
                                    f"pool/slot config too small for "
                                    f"{wave[0].rid} (free pages "
                                    f"{len(book._free)}, free slots "
                                    f"{len(free_slots)})")

                if active:
                    t0 = clock.now()
                    self._paged_chunk(book, clock, m, active, free_slots,
                                      slot_log, outputs, tr=tr,
                                      acache=acache, spst=spst,
                                      ahst=ahst, gcache=gcache)
                    est.observe("decode", clock.now() - t0)
                    t = clock.now()
                    for sid in list(active):
                        dl = active[sid].req.deadline_time()
                        if dl is not None and t > dl + 1e-9:
                            self._finish_paged(sid, book, clock, m,
                                               active, free_slots,
                                               slot_log, outputs,
                                               timeout=True, tr=tr,
                                               acache=acache,
                                               gcache=gcache)
                    progressed = True

                if lane:
                    _, ptoks = self._lane_step(
                        lane, book, clock, m, active, free_slots,
                        slot_log, outputs, prefix_cached, seen_groups,
                        tr=tr, acache=acache, spst=spst,
                        gcache=gcache)
                    prefill_tokens += ptoks
                    self._lane_timeouts(lane, book, clock, m,
                                        free_slots, slot_log, outputs,
                                        tr=tr, acache=acache,
                                        gcache=gcache)
                    progressed = True

                if not progressed and not active:
                    targets = []
                    if pending:
                        targets.append(pending[0].arrival)
                    if sched.waiting():
                        targets.append(sched.oldest_arrival()
                                       + self.admission.max_delay)
                    if not targets:
                        break  # everything left this turn was shed
                    clock.advance_to(min(targets))
                self._quant_turn(book, m, clock, tr, qst)
                inv_ok &= book.census_ok()
                if acache is not None:
                    a_inv &= acache.census_ok()
                if gcache is not None:
                    g_inv &= gcache.census_ok()
                if self._ledger is not None:
                    self._ledger.sample_occupancy(
                        clock.label, book=book, acache=acache,
                        gcache=gcache,
                        arena=getattr(book, "_arena", None))
        finally:
            if tr is not None:
                if prev_tr is not None:
                    obs_trace.activate(prev_tr)
                else:
                    obs_trace.deactivate()
        cost_stats = self._cost_result(clock, tr, m)
        self._close_trace(tr)
        self._stitch_resumes(outputs, hst)
        return ServeResult(policy=self.policy.name, outputs=outputs,
                           metrics=m, decisions=decisions,
                           slot_log=slot_log,
                           prefix_cached=prefix_cached,
                           pages_total=pages_total,
                           pages_free_end=(len(book._free)
                                           + len(book._evictable)),
                           scheduler=sched.name, shed=shed_log,
                           trace=tr, prefill_tokens=prefill_tokens,
                           cache_stats=dict(book.cache_stats(),
                                            invariant_ok=inv_ok),
                           incidents=self._bank_incidents(mon),
                           adapter_stats=(
                               None if acache is None else
                               dict(acache.cache_stats(),
                                    invariant_ok=a_inv)),
                           spec_stats=(None if spst is None
                                       else spst.stats()),
                           kv_quant_stats=self._quant_result(book,
                                                             qst),
                           overhead=self._overhead_row(clock, run_w0),
                           hostmem_stats=self._hostmem_result(book,
                                                              hst),
                           pages_spilled=(
                               None if hst is None else
                               book.cache_stats().get(
                                   "spilled_pages", 0)),
                           grammar_stats=(
                               None if gcache is None else
                               dict(gcache.cache_stats(),
                                    invariant_ok=g_inv)),
                           cost_stats=cost_stats)

    def _commit_wave(self, admitted, dec, sched, m, tr=None, t=0.0):
        """Charge the fair-queue tags for what actually ran (the
        degraded budget when a tier fired) and record degradations
        only then — a wave member blocked on slots stays queued,
        uncharged, and may re-degrade differently next turn. With a
        cost ledger armed, the scheduler's admission price is banked
        on the request's account here — commit is the moment the
        estimate became a promise — feeding the estimator-vs-actual
        calibration report."""
        for r in admitted:
            sched.commit(r.rid, budget=r.max_new_tokens)
            if self._ledger is not None:
                priced = sched.priced(r.rid) \
                    if hasattr(sched, "priced") else None
                if priced is not None:
                    self._ledger.note_estimate(r.rid, priced)
            if r.rid in dec.degraded:
                b, b0 = dec.degraded[r.rid]
                m.on_degrade(r.rid, b, b0)
                if tr is not None:
                    tr.instant("degrade", t=t, track="scheduler",
                               rid=r.rid, budget=b, orig_budget=b0,
                               tenant=r.tenant)

    def _sched_ready(self, sched, pending, active, clock) -> bool:
        if sched.waiting() >= self.admission.max_batch:
            return True
        # same-rounding rule as _admission_ready (see comment there)
        if clock.now() >= sched.oldest_arrival() \
                + self.admission.max_delay - 1e-12:
            return True
        return not pending and not active

    def _preempt_turn(self, blocked, book, clock, m, active,
                      free_slots, slot_log, sched, hst, shed_fn,
                      tr=None, acache=None, gcache=None) -> bool:
        """The QoS rung between degrade and shed: a wave the pool/slots
        fully blocked asks the scheduler for ONE strictly-lower-priority
        running victim, swaps its chain out to the host arena (pinned
        under its rid — the only K/V copy), releases its slot and pages,
        and requeues it carrying its emitted tokens (the PR-7
        resume-from-prefix arithmetic; re-admission swaps the chain
        back in instead of recomputing it). One victim per turn keeps
        the actuation deterministic and observable. Returns True when
        a victim actually swapped out."""
        running = [(sid, row.req, len(row.out))
                   for sid, row in active.items()]
        vic = sched.preempt_victim(clock.now(), blocked, running)
        if vic is None:
            return False
        row = active[vic]
        r = row.req
        keep = len(row.out)
        # the resumed request must still fit one slot (padded longer
        # prompt + remaining budget) — decline otherwise
        if self._footprint_len(len(r.prompt) + keep,
                               r.max_new_tokens - keep) > self.max_len:
            return False
        history = list(r.prompt) + list(row.out)
        keys = book.spill_chain(vic, history, owner=vic)
        if not keys and int(book.lengths.get(vic, 0)) >= self.page_size:
            # the arena refused (atomically — nothing moved): a swap
            # that would DISCARD the chain is a worse shed, so the
            # victim keeps decoding and the blocked request waits for
            # ordinary finishes
            return False
        # tear the row down WITHOUT finishing it: pages freed (their
        # content is safe in the arena), slot released, no on_finish —
        # the request is still live, just queued again
        active.pop(vic)
        book.free(vic)
        self._g_resident.set(float(len(book._refs)))
        if acache is not None and r.adapter is not None:
            acache.release(r.adapter, vic)
            self._note_adapters(acache, m, clock.now())
        if gcache is not None and row.gname is not None:
            # the automaton pin rolls off with the row; the DFA state
            # itself needs no spill — re-admission re-walks it from
            # the resume prefix (host arithmetic, no device work)
            gcache.release(row.gname, vic)
        free_slots.append(row.slot)
        free_slots.sort()
        t = clock.now()
        slot_log.append((round(t, 6), "release", vic, row.slot))
        hst["preempts"] += 1
        self._ctr_preempts.inc()
        m.on_preempt(vic, t, emitted=keep)
        hst["resume_prefix"][vic] = (hst["resume_prefix"].get(vic, [])
                                     + list(row.out))
        hst["preempted"].add(vic)
        if tr is not None:
            tr.add_span(vic, row.t0, t - row.t0,
                        track=f"slot/{row.slot}", backend="paged")
            tr.instant("preempt", t=t, track="scheduler", rid=vic,
                       emitted=keep, pages_spilled=len(keys),
                       tenant=r.tenant)
        res = dataclasses.replace(
            r, prompt=tuple(history),
            max_new_tokens=r.max_new_tokens - keep,
            cancel_after=(max(1, r.cancel_after - keep)
                          if r.cancel_after is not None else None))
        shed_fn(sched.enqueue(res, t))
        return True

    # --- paged backend ----------------------------------------------------
    def _admit_paged(self, wave, book, clock, m, active, free_slots,
                     slot_log, prefix_cached, seen_groups, outputs,
                     tr=None, lane=None, sink=None, acache=None,
                     spst=None, hst=None, gcache=None):
        """Returns (admitted, prefill chunks computed, prefill tokens
        computed) for this wave. With ``lane`` (the async prefill
        lane), admission only RESERVES — pages, slot, bookkeeping —
        and parks the request in the lane; its chunks run later under
        ``_lane_step``'s per-turn budget, so this wave's prefill never
        stalls the decode batch (chunk counts are then accounted by
        the lane steps, not here). ``sink`` is the prefill-role
        handoff interceptor (see ``_prefill_complete``). ``acache``
        (multi-model serving): admission PINS the request's adapter
        in the device bank — a resident adapter is a free hit, a miss
        pays one paced ``adapter_upload`` on the virtual clock, and a
        bank whose every slot is pinned by in-flight rows requeues
        the wave exactly like a page-pool refusal."""
        admitted = 0
        chunks_done = 0
        tokens_done = 0
        for r in wave:
            if not free_slots:
                break
            sid = r.rid
            # adapter residency FIRST (it is the cheapest refusal):
            # pin-while-in-flight guarantees the bank slot outlives
            # this row; a rolled-back page allocate below releases
            # the pin so the requeue retries from a clean slate
            aslot, a_up = 0, False
            if acache is not None and r.adapter is not None:
                try:
                    # a miss's host->device upload runs INSIDE the
                    # timed wrapper: paced per upload on the fixed
                    # clock, real transfer time attributed to the
                    # adapter_upload span on the measured one (a
                    # later page-refusal retry HITS and never
                    # re-pays). Hit/upload COUNTING waits for the
                    # admission to actually succeed — see
                    # took_upload below.
                    aslot, a_up = acache.acquire(
                        r.adapter, sid,
                        timed=lambda f: self._timed(
                            tr, clock, "adapter_upload", f, rid=sid,
                            adapter=r.adapter))
                except MemoryError:
                    break  # every slot pinned: requeue, retry as
                    # rows finish and release their pins
            # grammar residency SECOND (same pin discipline, one tier
            # over): a resident automaton is a free hit, a miss pays
            # one paced grammar_compile (host DFA compile + mask-bank
            # upload), and a bank whose every slot is pinned requeues
            # the wave — rolling back the adapter pin first
            gname = self._schema_of(r) if gcache is not None else None
            gslot, g_up, gaut = 0, False, None
            if gname is not None:
                try:
                    gslot, g_up = gcache.acquire(
                        gname, sid,
                        timed=lambda f: self._timed(
                            tr, clock, "grammar_compile", f, rid=sid,
                            schema=gname))
                except MemoryError:
                    if acache is not None and r.adapter is not None:
                        acache.note_rollback(r.adapter, sid, a_up)
                    break
                gaut = gcache.automaton(gname)
            # AUTOMATIC prefix acquisition: every request probes the
            # pool's chain-hashed page cache (page-aligned exact match
            # gives token-level sharing with no trace tag;
            # prefix_group stays a routing hint only). A failed
            # allocate below MUST release these shared refs — the
            # free() in the except arm is the leak-proof rollback,
            # returning revived pages to the evictable pool so the
            # requeue retries from a clean slate.
            n_cached = 0
            if self.prefix_cache:
                n_cached = book.acquire_prefix(sid, list(r.prompt))
                if hst is not None:
                    # PRICED page-in: the spilled extension of the
                    # resident match swaps back into fresh device
                    # pages (one kv_pagein each) and counts as cached
                    # — the prefill resumes past it exactly as past a
                    # resident hit. A preempted request's swapped
                    # chain restores through this same path.
                    n_cached += book.page_in(
                        sid, list(r.prompt), n_cached,
                        lambda p, e, _s=sid: self._pagein_page(
                            p, e, _s, clock, m, tr, hst))
            ev0 = book._stats["evictions"]
            try:
                book.allocate(sid, self._footprint(r))
            except MemoryError:
                if self.prefix_cache:
                    # shared refs released, revived pages re-parked,
                    # hit/lookup stats unwound (the requeue must not
                    # inflate hit_rate)
                    book.rollback_acquire(sid, list(r.prompt))
                else:
                    book.free(sid)
                if acache is not None and r.adapter is not None:
                    # the adapter pin rolls back too; the upload — if
                    # one ran — stays resident (the retry hits) and
                    # is REMEMBERED so the successful admission still
                    # reports it as this request's upload
                    acache.note_rollback(r.adapter, sid, a_up)
                if gname is not None:
                    # same discipline for the automaton pin: the
                    # compile — if one ran — stays resident and is
                    # remembered for the retry's attribution
                    gcache.note_rollback(gname, sid, g_up)
                break
            d_ev = book._stats["evictions"] - ev0
            if d_ev:
                self._ctr_prefix_evictions.inc(d_ev)
                if tr is not None:
                    tr.instant("prefix_evict", t=clock.now(),
                               track="engine", pages=d_ev, rid=sid)
            book.lengths[sid] = len(r.prompt)
            if hst is not None and sid in hst["preempted"]:
                # the preempted request is BACK: leftover pinned pages
                # demote to ordinary spilled cache (the page-ins above
                # already priced the swap-in; whatever the pool could
                # not take re-prefills below, same tokens either way)
                hst["preempted"].discard(sid)
                book.unpin_spilled_owner(sid)
                hst["restores"] += 1
                self._ctr_restores.inc()
                m.on_restore(sid, clock.now())
                if tr is not None:
                    tr.instant("restore", t=clock.now(),
                               track="scheduler", rid=sid,
                               tenant=r.tenant)
            slot = free_slots.pop(0)
            T = self._pad_len(len(r.prompt))
            toks = np.zeros((1, T), np.int32)
            toks[0, :len(r.prompt)] = r.prompt
            pt = np.zeros((1, self.W), np.int32)
            table = book.tables[sid]
            pt[0, :len(table)] = table
            lens = np.asarray([len(r.prompt)], np.int32)
            resume = (n_cached // self.chunk_C) * self.chunk_C
            # the factory clamps resume so the FINAL chunk always runs
            # (last-position logits) — charge the clock for what it
            # actually computes
            n_chunks = (T - min(resume, T - self.chunk_C)) \
                // self.chunk_C
            # per-request adaptive spec verdict, decided ONCE at
            # admission (the policy's spec_route rule): the row's
            # route for its whole lifetime, modulo the run-level
            # enable gate
            sp = False
            if spst is not None:
                sp, _sp_rule = self.policy.spec_route(r, spst.cfg)
            if gaut is not None:
                # a constrained row always decodes PLAIN: the draft
                # proposes unmasked tokens the verify would reject
                # almost surely, and acceptance bookkeeping under a
                # mask would fork the emission rule — free rows in
                # the same wave keep their spec verdict
                sp = False
            # DFA state the first emitted token is masked by: the
            # start state, or — for a preempted request swapping back
            # in — the state its already-served tokens walked to (the
            # resume prefix is exactly the emitted stream)
            gstate = 0
            if gaut is not None:
                gstate = gaut.start
                if hst is not None and hst["resume_prefix"].get(sid):
                    gstate = gaut.walk(hst["resume_prefix"][sid])
            t_admit = clock.now()
            m.on_admit(sid, t_admit, "paged")
            if gname is not None:
                # one hit-or-compile event per ADMISSION, the
                # took_upload discipline: a compile paid by a
                # rolled-back earlier acquire is attributed here
                g_up = gcache.took_compile(sid, g_up)
                (self._ctr_grammar_compiles if g_up
                 else self._ctr_grammar_hits).inc()
                m.on_grammar(sid, gname, hit=not g_up)
            if acache is not None and r.adapter is not None:
                # one hit-or-upload event per ADMISSION: an upload
                # paid by a rolled-back earlier acquire is attributed
                # here, so every counter surface (registry, report,
                # cache_stats) tells the same story
                a_up = acache.took_upload(sid, a_up)
                (self._ctr_adapter_uploads if a_up
                 else self._ctr_adapter_hits).inc()
                m.on_adapter(sid, r.adapter, hit=not a_up)
            if tr is not None:
                attrs = {} if r.adapter is None \
                    else {"adapter": r.adapter}
                if spst is not None:
                    # the admit instant carries the verdict ONLY on
                    # spec-configured runs, so plain traces keep
                    # their event args exactly
                    attrs["spec"] = sp
                if gname is not None:
                    # schema tag ONLY on constrained rows — free rows
                    # and grammar-less runs keep their event args
                    # exactly (the trace_report waterfall reads it)
                    attrs["schema"] = gname
                tr.instant("admit", t=t_admit,
                           track=self._tenant_track(r), rid=sid,
                           backend="paged", slot=slot, cached=n_cached,
                           **attrs)
            if lane is not None:
                lane.append(_PrefillingRow(r, slot, t_admit, n_cached,
                                           resume, T, self.chunk_C,
                                           toks, pt, aslot=aslot,
                                           spec=sp, gslot=gslot,
                                           gname=gname, gaut=gaut,
                                           gstate=gstate))
                admitted += 1
                continue

            def _call(toks=toks, pt=pt, lens=lens, resume=resume,
                      aslot=aslot, gslot=gslot, gstate=gstate):
                arr = self._arr
                kw = {}
                if acache is not None:
                    kw["lora"] = self._lora_arg(acache, [aslot])
                if gcache is not None:
                    kw["grammar"] = self._grammar_arg(
                        gcache, [gcache.flat_id(gslot, gstate)
                                 if gslot else 0])
                return self._p_prefill(
                    self._p_outer, self._p_layers, arr(toks),
                    arr(pt), arr(lens), self._pools,
                    resume_from=resume, **kw)
            first, self._pools = self._timed(
                tr, clock, "prefill", _call, jitfn=self._p_prefill,
                rid=sid, units=n_chunks, resume=resume,
                cached=n_cached, **self._tp_attr)
            first_tok = int(np.asarray(first)[0])
            chunks_done += n_chunks
            tokens_done += n_chunks * self.chunk_C
            self._prefill_complete(r, slot, first_tok, n_cached,
                                   resume, T, book, clock, m, active,
                                   free_slots, slot_log, outputs,
                                   prefix_cached, seen_groups, tr=tr,
                                   t0=t_admit, t_admit=t_admit,
                                   sink=sink, acache=acache,
                                   aslot=aslot, spst=spst,
                                   spec_row=sp, gcache=gcache,
                                   gslot=gslot, gname=gname,
                                   gaut=gaut, gstate=gstate)
            admitted += 1
        if admitted:
            self._g_resident.set(float(len(book._refs)))
            self._note_adapters(acache, m, clock.now())
        return admitted, chunks_done, tokens_done

    def _prefill_complete(self, r, slot, first_tok, n_cached, resume,
                          T, book, clock, m, active, free_slots,
                          slot_log, outputs, prefix_cached,
                          seen_groups, tr, t0, t_admit, sink=None,
                          acache=None, aslot=0, spst=None,
                          spec_row=False, gcache=None, gslot=0,
                          gname=None, gaut=None, gstate=0):
        """Everything that happens the moment a request's prompt pages
        hold real K/V: publish them for prefix sharing, account the
        cache hit, then either enter the decode slot (the default),
        finish outright (eos / a 1-token budget at the first token),
        or — when ``sink`` (a prefill-role session's handoff exporter)
        takes the row — hand the KV chain off instead of decoding.
        ``t0`` is the slot-occupancy span start: the admit time in the
        interleaved loop (whose slot span covers the prefill), the
        decode-entry time under the async lane (whose ``prefill_lane``
        span covers admit→here instead)."""
        sid = r.rid
        if self.prefix_cache:
            book.register_prefix(sid, list(r.prompt))
        if r.prefix_group is not None:
            seen_groups.add(r.prefix_group)
        if n_cached:
            self._ctr_prefix_hits.inc(n_cached)
        m.on_prefix(sid, cached=n_cached,
                    saved=min(resume, T - self.chunk_C),
                    prompt=len(r.prompt))
        prefix_cached[sid] = n_cached
        # a row joins the spec path only if the route is LIVE at its
        # prefill: a parked route (overload) or a latched one would
        # decode it plain — running the draft walk anyway would waste
        # compute on a row whose first plain turn demotes it (see
        # _paged_chunk), and skipping the walk while still flagging
        # it spec would hand the draft an unwarmed pool. A
        # prefill-ROLE session (sink set) never specs either: its
        # rows hand off to a decode worker that recreates them plain,
        # so a draft walk here would be compute the fleet never
        # cashes (disaggregated spec is future work).
        sp = bool(spec_row and spst is not None and spst.enabled
                  and not spst.latched and sink is None
                  and gaut is None)
        if sp:
            self._spec_prefill_row(r, book, T, clock, tr)
        row = _PagedRow(r, slot, first_tok, t0=t0, aslot=aslot,
                        spec=sp, prev=int(r.prompt[-1]), gslot=gslot,
                        gname=gname, gaut=gaut, gstate=gstate)
        g_mf = 0.0
        if gaut is not None:
            # the first token was emitted under gstate's mask — step
            # the DFA host-side; acceptance ends the stream like eos
            g_mf = gaut.masked_frac(gstate)
            row.gmasked += g_mf
            row.gstate = gaut.step(gstate, first_tok)
        done = len(row.out) >= row.eff \
            or first_tok == self.eos_token_id \
            or (gaut is not None and gaut.accepts_at(row.gstate))
        # a request DONE at its first token never hands off — the
        # stream is complete where it stands, there is no decode
        # phase to move
        if sink is not None and not done \
                and sink(r, slot, first_tok, n_cached, t_admit):
            return None
        active[sid] = row
        slot_log.append((round(clock.now(), 6), "acquire", sid, slot))
        t_first = clock.now()
        m.on_tokens(sid, t_first, 1)
        self._ctr_tokens.inc()
        if gaut is not None:
            m.on_grammar_tokens(1, g_mf)
            if gaut.accepts_at(row.gstate):
                m.on_grammar_accept(sid, t_first)
                if tr is not None:
                    tr.instant("grammar_accept", t=t_first,
                               track=self._tenant_track(r), rid=sid,
                               schema=gname)
        if tr is not None:
            tr.instant("first_token", t=t_first,
                       track=self._tenant_track(r), rid=sid)
        if done:
            self._finish_paged(sid, book, clock, m, active,
                               free_slots, slot_log, outputs, tr=tr,
                               acache=acache, gcache=gcache)
        return row

    def _lane_step(self, lane, book, clock, m, active, free_slots,
                   slot_log, outputs, prefix_cached, seen_groups,
                   tr=None, sink=None, acache=None, spst=None,
                   gcache=None):
        """Run up to ``prefill_chunk_budget`` prefill chunks from the
        lane, SHORTEST-REMAINING-FIRST (admission order breaking
        ties): a one-chunk prompt reaches its first token in one lane
        turn instead of queueing behind a long prompt's whole chunk
        walk — head-of-line blocking is exactly the TTFT tax the lane
        exists to remove. Starvation is BOUNDED by aging: an entry
        passed over ``_LANE_STARVE_LIMIT`` consecutive times runs its
        next chunk regardless, so a long prefill drains at >= 1 chunk
        per (limit+1) chunks even under a sustained stream of short
        arrivals. Each chunk is ONE bounded call into the
        chunked-prefill program — the prompt sliced to the chunk
        boundary with ``lengths`` clamped to it — which computes
        exactly what the monolithic prefill computes for those
        positions (causal attention never looks past the chunk, so
        greedy tokens are bit-equal); a request's own chunks still
        run in order, and its final chunk passes the true length and
        yields the real first-token logits. Fixed-clock pricing: with
        a ``prefill_unit`` entry each chunk costs one unit; with only
        a flat per-call cost, that cost is split EVENLY across the
        request's chunk calls, so the lane charges the same total the
        monolithic call would (an N-chunk prompt must not become N
        times pricier just because the lane bounds its calls).
        Returns (chunks computed, prompt tokens computed)."""
        if self.ragged_prefill:
            return self._lane_step_ragged(
                lane, book, clock, m, active, free_slots, slot_log,
                outputs, prefix_cached, seen_groups, tr=tr, sink=sink,
                acache=acache, spst=spst, gcache=gcache)
        C = self.chunk_C
        chunks_run = 0
        tokens_run = 0
        flat = self.clock_mode == "fixed" \
            and "prefill_unit" not in (self.fixed_costs or {})
        while lane and chunks_run < self.prefill_chunk_budget:
            oldest = min(lane, key=lambda x: (x.t_admit, x.req.rid))
            if oldest.skipped >= self._LANE_STARVE_LIMIT:
                e = oldest
            else:
                e = min(lane, key=lambda x: (x.remaining_chunks(),
                                             x.t_admit, x.req.rid))
            if e is oldest:
                oldest.skipped = 0
            else:
                oldest.skipped += 1
            sid = e.req.rid
            k = e.next_chunk
            final = (k + 1 == e.n_chunks)
            toks = e.toks[:, :(k + 1) * C]
            lens = np.asarray(
                [len(e.req.prompt) if final else (k + 1) * C],
                np.int32)

            def _call(toks=toks, pt=e.pt, lens=lens, resume=k * C,
                      aslot=e.aslot, gslot=e.gslot, gstate=e.gstate):
                arr = self._arr
                kw = {}
                if acache is not None:
                    kw["lora"] = self._lora_arg(acache, [aslot])
                if gcache is not None:
                    # only the FINAL chunk's logits are harvested, so
                    # masking every chunk with the row's current gid
                    # is exact (intermediate chunks discard theirs)
                    kw["grammar"] = self._grammar_arg(
                        gcache, [gcache.flat_id(gslot, gstate)
                                 if gslot else 0])
                return self._p_prefill(
                    self._p_outer, self._p_layers, arr(toks),
                    arr(pt), arr(lens), self._pools,
                    resume_from=resume, **kw)
            first, self._pools = self._timed(
                tr, clock, "prefill", _call, jitfn=self._p_prefill,
                rid=sid, units=1, chunk=k, of=e.n_chunks,
                cost=((self.fixed_costs or {}).get("prefill", 1.0)
                      / e.run_chunks if flat else None),
                **self._tp_attr)
            e.next_chunk += 1
            chunks_run += 1
            tokens_run += C
            if not final:
                continue
            lane.remove(e)
            t_done = clock.now()
            if tr is not None:
                tr.add_span(sid, e.t_admit, t_done - e.t_admit,
                            track="prefill_lane", cached=e.n_cached)
            self._prefill_complete(
                e.req, e.slot, int(np.asarray(first)[0]), e.n_cached,
                e.resume, e.T, book, clock, m, active, free_slots,
                slot_log, outputs, prefix_cached, seen_groups, tr=tr,
                t0=t_done, t_admit=e.t_admit, sink=sink,
                acache=acache, aslot=e.aslot, spst=spst,
                spec_row=e.spec, gcache=gcache, gslot=e.gslot,
                gname=e.gname, gaut=e.gaut, gstate=e.gstate)
        if self._g_lane_depth is not None:
            self._g_lane_depth.set(float(len(lane)))
        m.on_lane_depth(clock.now(), len(lane))
        if tr is not None:
            tr.counter("prefill_lane_depth", len(lane), t=clock.now())
        return chunks_run, tokens_run

    def _lane_step_ragged(self, lane, book, clock, m, active,
                          free_slots, slot_log, outputs, prefix_cached,
                          seen_groups, tr=None, sink=None, acache=None,
                          spst=None, gcache=None):
        """The FUSED lane turn: every parked request's next pending
        chunk rides ONE fixed-shape ragged dispatch (row index = the
        request's reserved decode slot; per-row chunk tokens, resume
        offsets and lengths as jit data, so the program cache stays
        flat across admission mixes). ``prefill_chunk_budget`` bounds
        fused DISPATCHES per turn — a burst of k admissions advances
        k chunks per dispatch instead of queueing behind the serial
        chunk loop, which is exactly the burst-TTFT tax this path
        removes. No entry is ever passed over (the whole lane
        advances together), so the per-chunk path's anti-starvation
        aging bound holds trivially and ``skipped`` stays 0. Pricing
        is chunk-for-chunk identical to the per-chunk path: with a
        ``prefill_unit`` entry the dispatch charges one unit per
        fused chunk; with only a flat per-call cost it charges the
        SUM of each fused row's even per-chunk split. A request's own
        chunks still run in order (one per dispatch), and rows whose
        FINAL chunk ran complete individually — prefill-role sessions
        export each finished row's KVHandoff exactly as before.
        Returns (dispatches run, prompt tokens computed)."""
        C = self.chunk_C
        R = self.slots
        dispatches = 0
        tokens_run = 0
        flat = self.clock_mode == "fixed" \
            and "prefill_unit" not in (self.fixed_costs or {})
        while lane and dispatches < self.prefill_chunk_budget:
            picked = sorted(lane, key=lambda x: (x.t_admit, x.req.rid))
            toks = np.zeros((R, C), np.int32)
            starts = np.zeros((R,), np.int32)
            pt = np.zeros((R, self.W), np.int32)
            # idle rows ride as plain causal garbage over the reserved
            # page 0 (length C, start 0) — NOT length 0, which would
            # fully mask their attention rows
            lens = np.full((R,), C, np.int32)
            aids = np.zeros((R,), np.int32) if acache is not None \
                else None
            gids = np.zeros((R,), np.int32) if gcache is not None \
                else None
            finals = []
            for e in picked:
                e.skipped = 0
                k = e.next_chunk
                final = (k + 1 == e.n_chunks)
                toks[e.slot] = e.toks[0, k * C:(k + 1) * C]
                starts[e.slot] = k * C
                pt[e.slot] = e.pt[0]
                lens[e.slot] = len(e.req.prompt) if final \
                    else (k + 1) * C
                if aids is not None:
                    aids[e.slot] = e.aslot
                if gids is not None and e.gslot:
                    gids[e.slot] = gcache.flat_id(e.gslot, e.gstate)
                if final:
                    finals.append(e)

            def _call(toks=toks, starts=starts, pt=pt, lens=lens,
                      aids=aids, gids=gids):
                arr = self._arr
                kw = {}
                if acache is not None:
                    kw["lora"] = self._lora_arg(acache, aids)
                if gcache is not None:
                    kw["grammar"] = self._grammar_arg(gcache, gids)
                return self._p_prefill_ragged(
                    self._p_outer, self._p_layers, arr(toks),
                    arr(starts), arr(pt), arr(lens), self._pools,
                    **kw)
            firsts, self._pools = self._timed(
                tr, clock, "prefill", _call,
                jitfn=self._p_prefill_ragged, units=len(picked),
                ragged=len(picked),
                cost=([(self.fixed_costs or {}).get("prefill", 1.0)
                       / e.run_chunks for e in picked]
                      if flat else None),
                rids=[e.req.rid for e in picked],
                **self._tp_attr)
            if self._ledger is not None:
                for e in picked:
                    self._ledger.tag(e.req.rid, "ragged")
            firsts = np.asarray(firsts)
            for e in picked:
                e.next_chunk += 1
            dispatches += 1
            tokens_run += C * len(picked)
            t_done = clock.now()
            for e in finals:
                sid = e.req.rid
                lane.remove(e)
                if tr is not None:
                    tr.add_span(sid, e.t_admit, t_done - e.t_admit,
                                track="prefill_lane",
                                cached=e.n_cached)
                self._prefill_complete(
                    e.req, e.slot, int(firsts[e.slot]), e.n_cached,
                    e.resume, e.T, book, clock, m, active, free_slots,
                    slot_log, outputs, prefix_cached, seen_groups,
                    tr=tr, t0=t_done, t_admit=e.t_admit, sink=sink,
                    acache=acache, aslot=e.aslot, spst=spst,
                    spec_row=e.spec, gcache=gcache, gslot=e.gslot,
                    gname=e.gname, gaut=e.gaut, gstate=e.gstate)
        if self._g_lane_depth is not None:
            self._g_lane_depth.set(float(len(lane)))
        m.on_lane_depth(clock.now(), len(lane))
        if tr is not None:
            tr.counter("prefill_lane_depth", len(lane), t=clock.now())
        return dispatches, tokens_run

    def _lane_timeouts(self, lane, book, clock, m, free_slots,
                       slot_log, outputs, tr=None, acache=None,
                       gcache=None):
        """A lane entry whose deadline passes MID-PREFILL is evicted
        exactly like a running row past deadline (reason "timeout",
        pages and slot freed) — a state the interleaved loop cannot
        reach (its prefill is atomic at admission), so only the
        QoS-scheduled async lane scans for it. The stream is empty:
        no token was ever produced."""
        t = clock.now()
        for e in list(lane):
            dl = e.req.deadline_time()
            if dl is None or t <= dl + 1e-9:
                continue
            lane.remove(e)
            sid = e.req.rid
            book.free(sid)
            self._g_resident.set(float(len(book._refs)))
            if acache is not None and e.req.adapter is not None:
                acache.release(e.req.adapter, sid)
                self._note_adapters(acache, m, t)
            if gcache is not None and e.gname is not None:
                gcache.release(e.gname, sid)
            free_slots.append(e.slot)
            free_slots.sort()
            slot_log.append((round(t, 6), "release", sid, e.slot))
            outputs[sid] = []
            m.on_finish(sid, t, evicted=True, reason="timeout")
            self._ctr_finished["timeout"].inc()
            if tr is not None:
                tr.add_span(sid, e.t_admit, t - e.t_admit,
                            track="prefill_lane", timeout=True)
            self._req_close(tr, e.req, t, "timeout", 0)

    @staticmethod
    def _lane_backlog_cost(lane, est) -> float:
        """The admission-feasibility price of the prefill work already
        COMMITTED to the lane: a new candidate's service cannot start
        before the lane drains. Per-chunk priced when the estimator
        carries a unit cost; under flat per-call pricing each entry's
        remaining cost is its flat cost pro-rated by the chunks still
        to run — exactly what ``_lane_step`` will charge the clock, so
        feasibility verdicts agree with the clock they model."""
        if not lane:
            return 0.0
        unit = est.costs.get("prefill_unit")
        if unit is not None:
            return float(unit) * sum(e.remaining_chunks()
                                     for e in lane)
        return est.prefill * sum(e.remaining_chunks() / e.run_chunks
                                 for e in lane)

    # --- KV page export/import (the cluster handoff's data plane) ---------
    def export_kv_pages(self, page_ids):
        """Gather the pool content of ``page_ids`` for a KV handoff.
        A factory may provide its own ``export_kv_pages(pools, ids)``
        (``serving.sim`` does — numpy pools); the default handles the
        real llama factory's pools, whose every leaf is page-indexed
        on axis 2 ((L, Hkv, P, page_size, ...) arrays — int8
        data+scale tuples included)."""
        fn = getattr(self.serving, "export_kv_pages", None)
        ids = list(page_ids)
        if fn is not None:
            return fn(self._pools, ids)
        idx = jnp.asarray(ids, jnp.int32)
        return jax.tree_util.tree_map(lambda a: a[:, :, idx],
                                      self._pools)

    def import_kv_pages(self, page_ids, data):
        """Scatter a handoff's exported page content into THIS
        engine's pool at ``page_ids`` (the importer's freshly
        allocated chain). Counterpart of ``export_kv_pages``."""
        fn = getattr(self.serving, "import_kv_pages", None)
        ids = list(page_ids)
        if fn is not None:
            self._pools = fn(self._pools, ids, data)
            return
        idx = jnp.asarray(ids, jnp.int32)
        self._pools = jax.tree_util.tree_map(
            lambda a, d: a.at[:, :, idx].set(d), self._pools, data)

    # --- heterogeneous handoffs: the reshard-on-import transform ----------
    def handoff_steps(self, h: "KVHandoff"):
        """Which priced transform steps THIS engine would run to adopt
        ``h`` — the compatibility verdict that replaced the placement
        filters. Returns ``()`` for a twin (adopt as-is, the
        pre-hetero fast path, zero spans), an ordered tuple drawn from
        ``("kv_reshard", "kv_repage", "kv_transcode")`` for a
        transformable mismatch, or ``None`` for the pairings that
        still refuse:

        - a QUANTIZED source (int8 precision is unrecoverable → fp
          refused; no tier bits to lift → pressure refused; int8
          scales don't re-tier → the codec only adopts same-codec);
        - a PRESSURE chain across page geometries (its per-page tier
          bits have no token-resolution meaning, so a re-paged chain
          could not say which arena each new page reads from).

        Raises ``UnstampedHandoffError`` when the handoff never got
        its source geometry stamped — loud, instead of the pre-hetero
        silent match-nothing."""
        if int(getattr(h, "page_size", 0)) <= 0 \
                or int(getattr(h, "tp", 0)) <= 0:
            raise UnstampedHandoffError(h)
        steps = []
        if h.tp != self.tp_size:
            steps.append("kv_reshard")
        if h.page_size != self.page_size:
            if h.kv_quant == "pressure":
                return None
            steps.append("kv_repage")
        if h.kv_quant != self.kv_quant:
            if h.kv_quant is not None:
                return None
            steps.append("kv_transcode")
        return tuple(steps)

    def handoff_price(self, h: "KVHandoff", steps=None):
        """Price the transform steps this engine would run to adopt
        ``h``, in its OWN clock units — placement's scoring input.
        Mirrors ``EngineClock``'s fixed arithmetic exactly (per-page
        when the cost table carries a ``<kind>_unit`` entry, the flat
        per-call default otherwise), so the score and the charge the
        importer's clock will actually book can never disagree. The
        router adds none of this to ``t_arrive``: delivery stays
        ``kv_transfer``-priced, and the importer's clock charges the
        transform spans when the import runs — one source of truth
        per cost. ``None`` = untransformable."""
        if steps is None:
            steps = self.handoff_steps(h)
        if steps is None:
            return None
        costs = self.fixed_costs or {}
        n_dst = -(-len(h.req.prompt) // self.page_size)
        total = 0.0
        for kind in steps:
            units = h.n_pages if kind == "kv_reshard" else n_dst
            unit = costs.get(f"{kind}_unit")
            total += float(unit) * units if unit is not None \
                else float(costs.get(kind, 1.0))
        return total

    def reshard_kv_pages(self, data):
        """The ``kv_reshard`` data plane: gather an exported chain
        across the SOURCE mesh's kv-head shards into the canonical
        head-major layout. A factory may override
        (``reshard_kv_pages(data)`` — ``serving.sim``'s is the
        identity, one host array has no shards); the default pulls
        every leaf to a single host view (the cross-shard gather), and
        the import scatter re-splits it under THIS engine's own pool
        sharding (GSPMD does the distribution — the destination mesh
        width never appears in the data plane)."""
        fn = getattr(self.serving, "reshard_kv_pages", None)
        if fn is not None:
            return fn(data)
        return jax.tree_util.tree_map(np.asarray, data)

    def repage_kv_pages(self, data, page_size_from: int,
                        n_tokens: int):
        """The ``kv_repage`` data plane: refold an exported chain from
        the source page geometry to THIS engine's. Factory hook
        ``repage_kv_pages(data, ps_from, ps_to, n_tokens)`` when
        provided (the sim's token rows), the llama head-major
        arithmetic otherwise."""
        fn = getattr(self.serving, "repage_kv_pages", None)
        if fn is not None:
            return fn(data, page_size_from, self.page_size, n_tokens)
        return repage_kv_data(data, page_size_from, self.page_size,
                              n_tokens)

    def transcode_kv_pages(self, data, quant_from):
        """The ``kv_transcode`` data plane: re-encode a full-precision
        chain into THIS engine's codec (int8 scales / pressure arenas
        + tier bits stamped). Factory hook
        ``transcode_kv_pages(data, q_from, q_to)`` when provided (the
        sim's lossless identity), the llama ``_q8`` codec otherwise —
        the same codec the destination's own write path runs, so a
        transcoded page is bit-identical to one written in place."""
        fn = getattr(self.serving, "transcode_kv_pages", None)
        if fn is not None:
            return fn(data, quant_from, self.kv_quant)
        return transcode_kv_data(data, quant_from, self.kv_quant)

    def _paged_chunk(self, book, clock, m, active, free_slots, slot_log,
                     outputs, tr=None, acache=None, spst=None,
                     ahst=None, gcache=None):
        """One decode turn. With a spec route (``spst``), the active
        rows split into the PLAIN group (decode_n, exactly the legacy
        turn) and the SPEC group (one batched draft/verify round) —
        two fixed-shape programs, each compiled once, rows outside a
        group riding along as length-0 page-0 slots. ``spst=None``
        is the legacy turn bit-for-bit. ``ahst`` (dispatch-ahead
        only; refuses spec at construction) threads the double
        buffer through the plain turn."""
        rows = sorted(active.values(), key=lambda s: s.slot)
        spec_rows: List[_PagedRow] = []
        if spst is not None:
            self._spec_gate(spst, clock, tr)
            if spst.enabled:
                spec_rows = [st for st in rows if st.spec]
                if spec_rows:
                    rows = [st for st in rows if not st.spec]
            else:
                # a spec row that decodes even ONE plain turn is
                # DEMOTED for its remainder: plain turns advance the
                # target pool but write no draft K/V and move the
                # two-token feed's anchor, so re-entering the spec
                # group later would condition the draft on a stale
                # prev token and a holed cache — acceptance would
                # collapse and latch the route plain for everyone.
                # Re-enabling therefore applies to rows ADMITTED
                # after the incident clears, whose draft state is
                # contiguous by construction.
                for st in rows:
                    st.spec = False
        if rows:
            self._plain_decode_rows(rows, book, clock, m, active,
                                    free_slots, slot_log, outputs,
                                    tr=tr, acache=acache, ahst=ahst,
                                    gcache=gcache)
        if spec_rows:
            self._spec_decode_rows(spec_rows, book, clock, m, active,
                                   free_slots, slot_log, outputs,
                                   spst, tr=tr)

    def _decode_batch(self, rows, book, acache, gcache=None):
        """The fixed-shape decode batch for ``rows`` (host side):
        token feed, page tables, lengths, adapter ids, grammar flat
        state ids — the inputs a decode_n dispatch is a pure function
        of."""
        toks = np.zeros((self.slots,), np.int32)
        pt = np.zeros((self.slots, self.W), np.int32)
        lens = np.zeros((self.slots,), np.int32)
        # per-slot adapter ids (0 = identity slot): built only when
        # multi-model serving is on — this is the engine's hottest
        # loop and single-model replays never read it
        aids = np.zeros((self.slots,), np.int32) \
            if acache is not None else None
        # per-slot grammar flat ids (0 = the all-allow identity row):
        # free rows and empty slots mask with row 0 by construction
        gids = np.zeros((self.slots,), np.int32) \
            if gcache is not None else None
        for st in rows:
            table = book.tables[st.req.rid]
            pt[st.slot, :len(table)] = table
            lens[st.slot] = book.lengths[st.req.rid]
            toks[st.slot] = st.tok
            if aids is not None:
                aids[st.slot] = st.aslot
            if gids is not None and st.gaut is not None:
                gids[st.slot] = gcache.flat_id(st.gslot, st.gstate)
        return toks, pt, lens, aids, gids

    @staticmethod
    def _roster_fp(rows, book):
        """The dispatch-ahead roster fingerprint: a stashed decode
        batch is served only when every (rid, slot, length, feed
        token, adapter slot) it was dispatched from is still exactly
        the live state — admissions, finishes, evictions and handoffs
        all change it, so a stale stash can never be read."""
        return tuple((st.req.rid, st.slot,
                      int(book.lengths[st.req.rid]), int(st.tok),
                      int(st.aslot)) for st in rows)

    def _plain_decode_rows(self, rows, book, clock, m, active,
                           free_slots, slot_log, outputs, tr=None,
                           acache=None, ahst=None, gcache=None):
        n = self.decode_chunk
        if gcache is not None and any(st.gaut is not None
                                      for st in rows):
            # the DFA advances HOST-side: a constrained row's mask for
            # token k+1 depends on token k, so a wave with any
            # constrained row decodes one token per turn. n is a
            # static jit arg — this adds at most ONE extra program
            # cache entry total, flat in the number of schemas; and
            # greedy decode is chunking-invariant, so free rows in
            # the same wave still emit byte-identical streams.
            n = 1
        toks, pt, lens, aids, gids = self._decode_batch(
            rows, book, acache, gcache)
        served_ahead = (ahst is not None and ahst.emits is not None
                        and ahst.fp == self._roster_fp(rows, book))
        if served_ahead:
            # turn t+1's batch was dispatched before turn t's host
            # bookkeeping completed and the roster still matches:
            # serve the in-flight result. The measured clock charges
            # only the RESIDUAL wait (the overlap is the win); a
            # fixed clock prices it exactly like a fresh dispatch, so
            # virtual-clock replays are byte-identical.
            stash = (ahst.emits, None, self._pools)
            if clock.mode == "measured":
                # the overlapped device span started at dispatch, not
                # at this serve — credit the hidden part to dev_wall
                # so the host-overhead decomposition sees the overlap
                clock.dev_wall += max(
                    0.0, time.perf_counter() - ahst.wall0)

            def _call():
                return stash
        else:
            def _call():
                arr = self._arr
                kw = {}
                if acache is not None:
                    kw["lora"] = self._lora_arg(acache, aids)
                if gcache is not None:
                    kw["grammar"] = self._grammar_arg(gcache, gids)
                return self._p_decode_n(
                    self._p_outer, self._p_layers, arr(toks),
                    arr(pt), arr(lens), self._pools, n, **kw)
        attrs = dict(self._tp_attr)
        if served_ahead:
            attrs["ahead"] = True
        emits, _, self._pools = self._timed(
            tr, clock, "decode", _call, jitfn=self._p_decode_n,
            n=n, rows=len(rows),
            rids=[st.req.rid for st in rows], **attrs)
        emits = np.asarray(emits)  # (n, slots) greedy tokens
        t = clock.now()
        for st in rows:
            sid = st.req.rid
            taken = 0
            for k in range(n):
                if len(st.out) >= st.eff or st.done:
                    break
                tok = int(emits[k, st.slot])
                st.out.append(tok)
                taken += 1
                if st.gaut is not None:
                    # the mask the device just applied came from
                    # gstate; account it, then advance to the state
                    # the NEXT turn will mask with
                    mf = st.gaut.masked_frac(st.gstate)
                    st.gmasked += mf
                    m.on_grammar_tokens(1, mf)
                    st.gstate = st.gaut.step(st.gstate, tok)
                    if st.gaut.accepts_at(st.gstate):
                        st.done = True
                        m.on_grammar_accept(sid, t)
                        if tr is not None:
                            tr.instant(
                                "grammar_accept", t=t,
                                track=self._tenant_track(st.req),
                                rid=sid, schema=st.gname)
                if tok == self.eos_token_id:
                    st.done = True
            st.tok = int(emits[-1, st.slot])
            book.lengths[sid] += n  # all n K/V writes happened
            if taken:
                m.on_tokens(sid, t, taken)
                self._ctr_tokens.inc(taken)
            if st.done or len(st.out) >= st.eff:
                self._finish_paged(sid, book, clock, m, active,
                                   free_slots, slot_log, outputs,
                                   tr=tr, acache=acache,
                                   gcache=gcache)
        if ahst is not None:
            self._dispatch_ahead_turn(ahst, book, active, acache, n)

    def _dispatch_ahead_turn(self, ahst, book, active, acache, n):
        """Dispatch turn t+1's decode batch NOW, from the post-update
        slot state, before the caller's remaining host bookkeeping
        (lane prefill routing, admission, metrics) runs — the device
        computes while Python routes. Outside the clock: the work is
        priced when (and only when) the stash is served. Safe to be
        wrong: a speculative dispatch only writes each surviving
        row's OWN pages at positions >= its length (never read until
        that row's turn actually lands, when identical values would
        be rewritten anyway) and the reserved page 0; a roster change
        discards the stash and re-dispatches. The donated pool buffer
        is rebound immediately, exactly like a synchronous call."""
        ahst.clear()
        nxt = sorted(active.values(), key=lambda s: s.slot)
        if not nxt or any(st.spec for st in nxt):
            return
        toks, pt, lens, aids, _ = self._decode_batch(nxt, book, acache)
        ahst.wall0 = time.perf_counter()
        arr = self._arr
        emits, _, self._pools = self._p_decode_n(
            self._p_outer, self._p_layers, arr(toks), arr(pt),
            arr(lens), self._pools, n,
            **({} if acache is None else
               {"lora": self._lora_arg(acache, aids)}))
        ahst.emits = emits
        ahst.fp = self._roster_fp(nxt, book)

    def _spec_decode_rows(self, rows, book, clock, m, active,
                          free_slots, slot_log, outputs,
                          spst: _SpecState, tr=None):
        """One speculative round for the spec group: the draft
        proposes ``n_draft`` tokens per row (two-token feed + in-jit
        walk), the target verifies them in ONE batched block, and
        each row advances by its accepted prefix + the correction
        token — 1..n_draft+1 tokens for one ``spec_decode`` clock
        action, vs ``decode_chunk`` tokens per ``decode``. Greedy
        acceptance keeps every token EXACTLY the target's greedy
        token (speculation changes latency, never content); rejected
        K/V — in both pools — sits beyond the advanced length and is
        overwritten by later writes, the PR-1 rollback-free
        invariant."""
        k = spst.cfg.n_draft
        prev = np.zeros((self.slots,), np.int32)
        toks = np.zeros((self.slots,), np.int32)
        pt = np.zeros((self.slots, self.W), np.int32)
        lens = np.zeros((self.slots,), np.int32)
        for st in rows:
            table = book.tables[st.req.rid]
            pt[st.slot, :len(table)] = table
            lens[st.slot] = book.lengths[st.req.rid]
            toks[st.slot] = st.tok
            prev[st.slot] = st.prev
        s_outer, s_layers = self._spec_parts[0], self._spec_parts[1]
        s_step = self._spec_parts[4]

        def _call():
            arr = self._arr
            return s_step(self._p_outer, self._p_layers, s_outer,
                          s_layers, arr(prev), arr(toks), arr(pt),
                          arr(lens), self._pools, self._spec_pools,
                          k)
        counts, cands, self._pools, self._spec_pools = self._timed(
            tr, clock, "spec_decode", _call, jitfn=s_step, k=k,
            rows=len(rows),
            rids=[st.req.rid for st in rows], **self._tp_attr)
        counts = np.asarray(counts)
        cands = np.asarray(cands)
        t = clock.now()
        turn_prop = turn_acc = 0
        for st in rows:
            sid = st.req.rid
            n = int(counts[st.slot])
            cand = cands[st.slot]
            taken = 0
            for i in range(n + 1):
                if len(st.out) >= st.eff or st.done:
                    break
                tok = int(cand[i])
                st.out.append(tok)
                taken += 1
                if tok == self.eos_token_id:
                    st.done = True
            # position bookkeeping: all n+1 verified positions hold
            # real K/V (position L took st.tok, L+1+i took d_i for
            # i < n); the new last token t_n sits at position L+n+1,
            # not yet written — exactly decode_n's lengths discipline
            st.prev = int(cand[n - 1]) if n >= 1 else st.tok
            st.tok = int(cand[n])
            book.lengths[sid] += n + 1
            st.sprop += k
            st.sacc += n
            turn_prop += k
            turn_acc += n
            if taken:
                m.on_tokens(sid, t, taken)
                self._ctr_tokens.inc(taken)
            if st.done or len(st.out) >= st.eff:
                self._finish_paged(sid, book, clock, m, active,
                                   free_slots, slot_log, outputs,
                                   tr=tr)
        spst.note(len(rows), turn_prop, turn_acc)
        m.on_spec(len(rows), turn_prop, turn_acc)
        self._ctr_spec_rounds.inc(len(rows))
        self._ctr_draft_proposed.inc(turn_prop)
        self._ctr_draft_accepted.inc(turn_acc)

    def _finish_paged(self, sid, book, clock, m, active, free_slots,
                      slot_log, outputs, timeout: bool = False,
                      tr=None, acache=None, gcache=None):
        st = active.pop(sid)
        book.free(sid)
        self._g_resident.set(float(len(book._refs)))
        if acache is not None and st.req.adapter is not None:
            # unpin: the adapter is RETAINED evictable (the next
            # sharer hits), reclaimed only under bank pressure
            acache.release(st.req.adapter, sid)
            self._note_adapters(acache, m, clock.now())
        if gcache is not None and st.gname is not None:
            # same retention discipline as adapters: the automaton
            # stays resident-evictable for the schema's next sharer
            gcache.release(st.gname, sid)
        free_slots.append(st.slot)
        free_slots.sort()
        slot_log.append((round(clock.now(), 6), "release", sid, st.slot))
        outputs[sid] = st.out
        r = st.req
        evicted = (r.cancel_after is not None
                   and st.eff == r.cancel_after
                   and st.eff < r.max_new_tokens and not st.done)
        # a deadline timeout is the same eviction path as client churn
        # (cancel_after): stop decoding, free pages, mark evicted —
        # only the recorded reason differs
        t_fin = clock.now()
        m.on_finish(sid, t_fin, evicted=evicted or timeout,
                    reason="timeout" if timeout
                    else ("cancel" if evicted else None))
        outcome = "timeout" if timeout else (
            "cancel" if evicted else "completed")
        self._ctr_finished[outcome].inc()
        if tr is not None:
            tr.add_span(sid, st.t0, t_fin - st.t0,
                        track=f"slot/{st.slot}", backend="paged")
            if st.sprop > 0:
                # per-request spec evidence for trace_report's
                # accept=a/p waterfall column — emitted ONLY when the
                # row actually ran spec rounds, so plain traces keep
                # their event set exactly
                tr.instant("spec", t=t_fin,
                           track=self._tenant_track(r), rid=sid,
                           proposed=st.sprop, accepted=st.sacc)
        self._req_close(tr, r, t_fin, outcome, len(st.out))

    def session(self, *, tracer=None, replica: Optional[str] = None,
                expect_churn: bool = False, role: str = "both",
                slo=None) -> "EngineSession":
        """An incremental session over this engine's configuration —
        the cluster router's entry point (see ``EngineSession``).
        ``role`` is the disaggregation stage this session serves
        ("prefill" exports finished prefills as KV handoffs, "decode"
        adopts them, "both" is the classic replica). ``slo`` is this
        replica's ``obs.slo.SLOMonitor`` (the cluster router builds
        one per replica over a shared IncidentLog); it observes the
        session's metrics stream and never mutates it. With ``slo``
        unset, an engine constructed with ``ServingEngine(slo=...)``
        monitors its sessions too — both run paths see the same
        watchdog config. The engine object itself is untouched;
        ``run()`` keeps replaying traces byte-identically."""
        if slo is None:
            slo = self._make_monitor(fresh=False)
        return EngineSession(self, tracer=tracer, replica=replica,
                             expect_churn=expect_churn, role=role,
                             slo=slo)

    # --- dense backend ----------------------------------------------------
    def _run_dense_wave(self, wave, clock, m, outputs,
                        timeouts: bool = False, tr=None):
        """A wave on the dense compiled cache: equal-length groups batch
        together (the dense prefill needs one S0 per program); each
        group runs prefill + per-token decode to the LONGEST effective
        budget in the group — short-budget rows ride along, which is
        exactly the dense tax on mixed traffic that the router prices.
        The wave runs start-to-finish (dense slots cannot admit or
        evict mid-stream); arrivals meanwhile queue.

        ``timeouts`` (the QoS-scheduled loop only): a row whose
        deadline passes mid-wave stops STREAMING at that point — like
        ``cancel_after``, the batch keeps computing but the row takes
        no more tokens and is marked evicted with reason "timeout", so
        the goodput/timeout accounting matches the paged path even
        though dense cannot free resources mid-stream."""
        parts = self._dense
        dtype = parts["outer"]["model.embed_tokens.weight"].dtype
        groups: Dict[int, List[Request]] = {}
        for r in wave:
            groups.setdefault(len(r.prompt), []).append(r)
        for S0 in sorted(groups):
            grp = groups[S0]
            B = len(grp)
            toks = np.asarray([r.prompt for r in grp], np.int32)
            kc = parts["init_caches"](B, dtype)
            vc = parts["init_caches"](B, dtype)
            t_admit = clock.now()
            for r in grp:
                m.on_admit(r.rid, t_admit, "dense")
                if tr is not None:
                    tr.instant("admit", t=t_admit,
                               track=self._tenant_track(r),
                               rid=r.rid, backend="dense")

            def _pf(kc=kc, vc=vc):
                return parts["prefill"](parts["outer"], parts["layers"],
                                        jnp.asarray(toks), kc, vc)
            logits, kc, vc = self._timed(
                tr, clock, "dense_prefill", _pf,
                jitfn=parts["prefill"], S0=S0, B=B,
                rids=[r.rid for r in grp])
            cur = np.argmax(np.asarray(logits), -1).astype(np.int32)
            t = clock.now()
            outs = [[int(c)] for c in cur]
            eff = [min(r.max_new_tokens,
                       r.cancel_after if r.cancel_after is not None
                       else 10 ** 9) for r in grp]
            dls = [r.deadline_time() if timeouts else None
                   for r in grp]
            timed = [False] * B
            fin: List[Optional[float]] = [None] * B
            eos_hit = [False] * B
            for i, r in enumerate(grp):
                m.on_tokens(r.rid, t, 1)
                self._ctr_tokens.inc()
                if tr is not None:
                    tr.instant("first_token", t=t,
                               track=self._tenant_track(r), rid=r.rid)
                if outs[i][0] == self.eos_token_id:
                    eos_hit[i] = True
                if len(outs[i]) >= eff[i] or eos_hit[i]:
                    fin[i] = t
                elif dls[i] is not None and t > dls[i] + 1e-9:
                    fin[i] = t
                    timed[i] = True
            pos = S0
            while any(f is None for f in fin):
                def _st(cur=cur, pos=pos, kc=kc, vc=vc):
                    return parts["decode_step"](
                        parts["outer"], parts["layers"],
                        jnp.asarray(cur), jnp.asarray(pos), kc, vc)
                logits, kc, vc = self._timed(
                    tr, clock, "dense_decode", _st,
                    jitfn=parts["decode_step"], B=B,
                    rids=[r.rid for r in grp])
                cur = np.argmax(np.asarray(logits), -1).astype(np.int32)
                pos += 1
                t = clock.now()
                for i, r in enumerate(grp):
                    if fin[i] is None:
                        tok = int(cur[i])
                        outs[i].append(tok)
                        m.on_tokens(r.rid, t, 1)
                        self._ctr_tokens.inc()
                        if tok == self.eos_token_id:
                            eos_hit[i] = True
                        if len(outs[i]) >= eff[i] or eos_hit[i]:
                            fin[i] = t
                        elif dls[i] is not None and t > dls[i] + 1e-9:
                            fin[i] = t
                            timed[i] = True
            t_end = clock.now()
            if tr is not None:
                tr.add_span("dense_wave", t_admit, t_end - t_admit,
                            track="waves", S0=S0, B=B)
            for i, r in enumerate(grp):
                outputs[r.rid] = outs[i]
                evicted = (r.cancel_after is not None
                           and eff[i] == r.cancel_after
                           and eff[i] < r.max_new_tokens
                           and not eos_hit[i])
                m.on_finish(r.rid, fin[i], evicted=evicted or timed[i],
                            reason="timeout" if timed[i]
                            else ("cancel" if evicted else None))
                outcome = "timeout" if timed[i] else (
                    "cancel" if evicted else "completed")
                self._ctr_finished[outcome].inc()
                self._req_close(tr, r, fin[i], outcome, len(outs[i]))


class EngineSession:
    """One INCREMENTAL engine replay — the seam the cluster layer
    composes N replicas through.

    ``ServingEngine.run()`` replays a whole trace start-to-finish on a
    private clock; a session is the same arrive→admit→route→prefill→
    decode→finish lifecycle driven from outside, one event at a time:

    - ``submit(r)`` feeds one arrival (the router has already advanced
      this replica's clock to the arrival time);
    - ``advance_until(t)`` processes this replica's lane of the shared
      virtual timeline up to ``t`` — called for EVERY replica before
      each placement decision, so load/prefix probes answer "as of
      ``t``", not "as of whenever this replica last ran";
    - ``pull_unadmitted()`` hands the queued-but-never-admitted backlog
      back for placement elsewhere (the drain path; in-flight rows keep
      streaming);
    - ``finish()`` runs the backlog dry and builds the ``ServeResult``.

    Both admission disciplines drive through here — FIFO
    (``scheduler=None``) mirrors ``run()``'s loop body, a
    ``QoSScheduler`` mirrors ``_run_scheduled``'s (shedding, degrade
    tiers, cache-aware feasibility pricing, running-row timeouts). The
    single-engine loops are untouched and replay byte-identically.

    Each replica needs its OWN engine (and its own serving factory:
    factories share live pool buffers, and two sessions allocating page
    ids from independent bookkeepers over one buffer would corrupt each
    other's K/V). Timestamps are always explicit, so one shared cluster
    ``Tracer`` serves N per-replica clocks.

    Per-request metrics, outputs, decisions and slot logs match
    ``run()`` exactly on the same stream; the one sampled diagnostic
    that differs is queue-depth cadence (``run()`` also samples on
    pure arrival-ingestion iterations; a session samples once per
    turn), so ``queue_depth_mean`` is comparable but not bit-equal.
    """

    def __init__(self, engine: ServingEngine, *, tracer=None,
                 replica: Optional[str] = None,
                 expect_churn: bool = False, role: str = "both",
                 slo=None):
        if role not in ("prefill", "decode", "both"):
            raise ValueError(f"role {role!r}: use 'prefill', 'decode' "
                             "or 'both'")
        eng = self.eng = engine
        self.replica = replica
        # --- disaggregation (all inert at role="both") --------------
        # "prefill": every finished prefill EXPORTS its KV chain as a
        # KVHandoff (banked in handoff_ready for the router) instead
        # of entering a decode slot. "decode": this session never
        # receives admissions from a disaggregated placement policy —
        # it adopts handoffs through submit_handoff/import_queue and
        # only decodes. "both" is the classic replica.
        self.role = role
        self.lane = deque() if eng.prefill_chunk_budget is not None \
            else None
        self.handoff_ready: List[KVHandoff] = []
        self.import_queue: List[KVHandoff] = []
        self.handoff_stats = {"imported": 0, "reclaimed": 0}
        # axis -> transform-step count ("tp"/"page"/"codec"); stays
        # EMPTY on a twin fleet — the armed-only convention, folded
        # into the router's census like handoff_stats
        self.handoff_resharded: Dict[str, int] = {}
        self.clock = eng._make_clock(replica or "engine")
        self.tr = tracer
        self.slo = slo
        self.m = MetricsCollector(monitor=slo)
        self._g_busy = None
        if slo is not None:
            # the utilization gauge rides the monitored path only, so
            # unmonitored replays leave no trace of it in the
            # registry (PR-5 convention); the child is resolved once
            # here, not per turn
            self._g_busy = obs_metrics.REGISTRY.gauge(
                "serving_replica_busy_frac",
                "busy decode slots / slot capacity, sampled per turn",
                replica=replica or "-")
        self.book = PagedKVCache(eng.n_pool_pages, eng.page_size,
                                 kv_heads=1, head_dim=1)
        eng._note_pool(self.book, self.m)
        # per-session host arena (hostmem= engines; None otherwise):
        # each replica owns its spill tier — eviction spill, priced
        # page-in and the QoS preempt rung all work per session
        self.hst = eng._arm_hostmem(self.book, self.clock, self.m,
                                    tracer)
        # per-session adapter cache (multi-model serving; None when
        # the engine is single-model): each replica owns its bank —
        # residency is the signal adapter-aware placement routes on
        self.acache = eng._make_adapter_cache()
        # per-session grammar cache (constrained decoding; None when
        # the engine has no grammar store): each replica owns its
        # mask bank, so schema residency is per-replica too
        self.gcache = eng._make_grammar_cache()
        # per-session spec-route state (multi-replica: each replica
        # EWMAs its own acceptance and flips independently)
        self.spst = eng._make_spec_state()
        # per-session pressure-tier state (each replica watches its
        # own pool's byte census and flips/compacts independently)
        self.qst = eng._make_quant_state()
        # per-session dispatch-ahead double buffer (None with the
        # flag off — the turn is then the legacy sequential one)
        self.ahst = eng._make_ahead_state()
        self.pages_total = len(self.book._free)
        self.sched = eng.scheduler
        eng._wire_spec_overload(slo, self.sched)
        eng._wire_pressure(slo, self.sched)
        self.est: Optional[ServiceEstimator] = None
        if self.sched is not None:
            self.sched.reset()
            costs = eng.fixed_costs or {}
            est_kw = {}
            if "prefill_unit" in costs:
                est_kw = {"prefill_unit": costs["prefill_unit"],
                          "chunk_tokens": eng.chunk_C}
            self.est = ServiceEstimator(
                prefill=costs.get("prefill", 1.0),
                decode=costs.get("decode", 1.0), **est_kw)
        self.waiting: List[Request] = []   # FIFO discipline only
        self.active: Dict[str, _PagedRow] = {}
        self.free_slots = list(range(eng.slots))
        self.outputs: Dict[str, List[int]] = {}
        self.decisions: List[dict] = []
        self.slot_log: List[tuple] = []
        self.prefix_cached: Dict[str, int] = {}
        self.shed_log: Dict[str, str] = {}
        self.seen_groups: set = set()
        self.prefill_tokens = 0
        self.inv_ok = True
        # adapter-slot census flag, SEPARATE from the pool census so
        # a page leak is never reported as a bank-slot leak (and vice
        # versa)
        self.a_inv_ok = True
        # grammar-slot census flag, separate for the same reason
        self.g_inv_ok = True
        # True while the router may still submit here; finish() (and a
        # drain) clears it, enabling run()'s "nothing else will ever
        # come" admission clause
        self.more_expected = True
        self._ctx_base = {"capacity": eng.slots,
                          "expect_churn": bool(expect_churn)}
        self._finished: Optional[ServeResult] = None
        # --- fault-tolerance state (all inert on the happy path) ---
        # crashed: the replica process is DEAD — it queues submissions
        # (the router does not know yet) but processes nothing; its
        # in-flight rows were torn down at crash time into
        # crash_salvage for the router's failover to resume elsewhere.
        self.crashed = False
        self.crash_salvage: List[Tuple[Request, List[int]]] = []
        # arrivals routed here AFTER the crash (the router has not
        # detected the silence yet): no admission policy runs on a
        # dead process — they wait for pull_unadmitted, uncounted by
        # the scheduler
        self._dead_letter: List[Request] = []
        # stall_until: transient liveness-preserving pause — no turn
        # runs before this virtual time, but the session still answers
        # health probes (a stall is slow, not dead).
        self.stall_until: Optional[float] = None
        # decode_fault_hook: callable(session) invoked inside each
        # decode turn's try block; raising DecodeError(rid) from it
        # exercises the single-row teardown path. Aborted rows bank in
        # .aborted as (Request, emitted tokens) for the driver to
        # re-place.
        self.decode_fault_hook = None
        self.aborted: List[Tuple[Request, List[int]]] = []

    # --- placement probes --------------------------------------------------
    def queued(self) -> int:
        n = self.sched.waiting() if self.sched is not None \
            else len(self.waiting)
        return n + len(self._dead_letter)

    def load(self) -> int:
        """The live load signal placement policies read: queued +
        in-flight requests on this replica (prefilling lane rows and
        accepted-but-not-imported handoffs included — both are work
        this replica owes)."""
        return self.queued() + self.in_flight()

    def in_flight(self) -> int:
        """Rows this session still owes work for: decoding rows,
        prefilling lane rows, and handoffs accepted but not yet
        imported."""
        return len(self.active) + len(self.lane or ()) \
            + len(self.import_queue)

    def free_slot_count(self) -> int:
        """Open decode slots right now — the signal the disaggregated
        placement's decode stage places handoffs by."""
        return len(self.free_slots)

    def prefill_backlog(self) -> int:
        """Pending prefill CHUNKS on this replica: the lane's
        remaining chunks plus every queued (not yet admitted) prompt's
        padded chunk count — what the disaggregated placement policy
        prices the prefill stage with (multiply by the estimator's
        ``prefill_unit`` for clock units)."""
        C = self.eng.chunk_C
        n = sum(e.remaining_chunks() for e in self.lane or ())
        reqs = self.sched.queued_requests() if self.sched is not None \
            else self.waiting
        for r in reqs:
            n += self.eng._pad_len(len(r.prompt)) // C
        return n

    def match_prefix(self, prompt) -> int:
        """Non-acquiring probe of THIS replica's paged pool: leading
        tokens of ``prompt`` its prefix cache could serve right now
        (0 when the engine runs cache-off)."""
        if not self.eng.prefix_cache:
            return 0
        return self.book.match_prefix(list(prompt))

    def adapter_resident(self, name) -> bool:
        """Non-acquiring probe of THIS replica's adapter bank: is
        ``name`` on device right now (pinned or retained)? The
        adapter-aware placement signal — False on a single-model
        session or for ``name=None``."""
        if self.acache is None or name is None:
            return False
        return self.acache.resident(name)

    # --- arrivals ----------------------------------------------------------
    def submit(self, r: Request):
        """One arrival (advance this lane to ``r.arrival`` first). On
        a CRASHED session the request dead-letters instead of entering
        the scheduler: a dead process cannot run admission policy, so
        it must never shed (a terminal rejection issued by a corpse
        would permanently drop a request the failover contract
        promises to rescue) — the dead letters leave with the queue
        at ``pull_unadmitted``."""
        eng = self.eng
        eng._validate([r])
        self.m.on_arrival(r.rid, r.arrival, tenant=r.tenant,
                          priority=r.priority,
                          deadline_ms=r.deadline_ms)
        eng._ctr_arrived.inc()
        eng._req_open(self.tr, r)
        if self.crashed:
            self._dead_letter.append(r)
        elif self.sched is not None:
            self._shed(self.sched.enqueue(r, self.clock.now()))
        else:
            self.waiting.append(r)

    def pull_unadmitted(self, outcome: str = "requeued") \
            -> List[Request]:
        """Drain/failover support: remove every queued-but-never-
        admitted request from this session — the queue entry, the
        metrics arrival record (it moves with the request, so a
        cluster rollup counts it ONCE, at wherever it finally runs or
        sheds) and the trace root (closed with ``outcome``: "requeued"
        for a graceful drain, "failover" when a dead replica's queue
        is rescued) — and return them in (arrival, rid) order.
        In-flight rows are untouched and keep streaming to completion
        (on a crashed session there are none left to touch)."""
        if self.sched is not None:
            reqs = self.sched.drain_queue()
        else:
            reqs = list(self.waiting)
            self.waiting = []
        reqs = sorted(reqs + self._dead_letter,
                      key=lambda r: (r.arrival, r.rid))
        self._dead_letter = []
        t = self.clock.now()
        for r in reqs:
            self.m.forget(r.rid)
            if self.acache is not None:
                self.acache.forget_pending(r.rid)
            if self.gcache is not None:
                self.gcache.forget_pending(r.rid)
            self.eng._req_close(self.tr, r, t, outcome, 0)
        # accepted-but-not-imported handoffs leave with the queue:
        # their exported KV is RECLAIMED (dropped — wherever the
        # request lands next re-prefills) and the request re-places;
        # it has no metrics record or open trace root HERE (the source
        # closed its root at export, the importer would have re-opened
        # one), so there is nothing to forget or close
        if self.import_queue:
            self.handoff_stats["reclaimed"] += len(self.import_queue)
            imports = [h.req for h in self.import_queue]
            self.import_queue = []
            reqs = sorted(reqs + imports,
                          key=lambda r: (r.arrival, r.rid))
        return reqs

    # --- fault teardown ----------------------------------------------------
    def abort_row(self, rid: str, reason: str = "decode_error") \
            -> Tuple[Request, List[int]]:
        """Tear down ONE in-flight row without corrupting survivors:
        its pool pages are released, its slot freed (logged as an
        "abort" slot event), its metrics record forgotten and its
        trace root closed with outcome "failover" — the request is
        MOVING, not finishing, so nothing lands in ``outputs`` and no
        finish counter fires. Returns (request, tokens emitted so
        far): the salvage a failover resumes from."""
        st = self.active.pop(rid)
        self.book.free(rid)
        eng = self.eng
        eng._g_resident.set(float(len(self.book._refs)))
        if self.acache is not None and st.req.adapter is not None:
            self.acache.release(st.req.adapter, rid)
            eng._note_adapters(self.acache, self.m, self.clock.now())
        if self.gcache is not None and st.gname is not None:
            self.gcache.release(st.gname, rid)
        self.free_slots.append(st.slot)
        self.free_slots.sort()
        t = self.clock.now()
        self.slot_log.append((round(t, 6), "abort", rid, st.slot))
        obs_metrics.REGISTRY.counter(
            "serving_rows_aborted_total",
            "in-flight rows torn down by crash/decode faults",
            reason=reason).inc()
        if self.tr is not None:
            self.tr.add_span(rid, st.t0, t - st.t0,
                             track=f"slot/{st.slot}", backend="paged",
                             aborted=reason)
        eng._req_close(self.tr, st.req, t, "failover", len(st.out),
                       reason=reason)
        self.m.forget(rid)
        self.inv_ok &= self.book.census_ok()
        return st.req, list(st.out)

    def crash(self) -> None:
        """The replica process dies NOW (distinct from drain: nothing
        is handed anywhere — the router's failure detector must notice
        the silence). Every in-flight row is torn down into
        ``crash_salvage`` (admission order, so failover is
        deterministic), then the pool is PURGED — retained prefix
        pages included, with the epoch bumped, because a dead
        replica's K/V cannot serve anyone — and the session stops
        processing. Submissions still queue here (the router does not
        know yet); ``pull_unadmitted`` rescues them at detection."""
        if self.crashed:
            raise RuntimeError("session already crashed")
        self.crashed = True
        for rid in sorted(self.active,
                          key=lambda r: (self.active[r].t0, r)):
            self.crash_salvage.append(
                self.abort_row(rid, reason="replica_crash"))
        # prefilling lane rows die with the pool: no token was ever
        # emitted, so their salvage is an empty stream (admit order —
        # deterministic failover, after the decoding rows)
        for e in list(self.lane or ()):
            self.lane.remove(e)
            self.crash_salvage.append(
                self._abort_lane_entry(e, reason="replica_crash"))
        # accepted-but-not-imported handoffs: the exported KV dies
        # here unlanded (reclaimed); the REQUEST fails over and
        # re-prefills on a survivor — accounted, never lost
        if self.import_queue:
            self.handoff_stats["reclaimed"] += len(self.import_queue)
            for h in self.import_queue:
                self.crash_salvage.append((h.req, []))
            self.import_queue = []
        self.book.purge()
        self.inv_ok &= self.book.census_ok()

    def _abort_lane_entry(self, e: _PrefillingRow, reason: str) \
            -> Tuple[Request, List[int]]:
        """Tear down ONE prefilling lane row (the lane twin of
        ``abort_row``): pages freed, slot released ("abort" slot
        event), metrics record forgotten, trace root closed with
        outcome "failover" — the request is moving, not finishing.
        Salvage is always the empty stream: no token existed yet."""
        sid = e.req.rid
        self.book.free(sid)
        eng = self.eng
        eng._g_resident.set(float(len(self.book._refs)))
        if self.acache is not None and e.req.adapter is not None:
            self.acache.release(e.req.adapter, sid)
            eng._note_adapters(self.acache, self.m, self.clock.now())
        if self.gcache is not None and e.gname is not None:
            self.gcache.release(e.gname, sid)
        self.free_slots.append(e.slot)
        self.free_slots.sort()
        t = self.clock.now()
        self.slot_log.append((round(t, 6), "abort", sid, e.slot))
        obs_metrics.REGISTRY.counter(
            "serving_rows_aborted_total",
            "in-flight rows torn down by crash/decode faults",
            reason=reason).inc()
        if self.tr is not None:
            self.tr.add_span(sid, e.t_admit, t - e.t_admit,
                             track="prefill_lane", aborted=reason)
        eng._req_close(self.tr, e.req, t, "failover", 0, reason=reason)
        self.m.forget(sid)
        self.inv_ok &= self.book.census_ok()
        return e.req, []

    # --- KV handoff (the disaggregated prefill->decode seam) --------------
    def _handoff_sink(self, r: Request, slot: int, first_tok: int,
                      n_cached: int, t_admit: float) -> bool:
        """The prefill-role completion path: export the prompt's page
        chain, free the row's pages and slot (the KV MOVED — the
        registered prefix pages stay retained in this pool's evictable
        LRU, so later sharers still skip their prefill here), move the
        metrics record and trace root out (forgotten here, re-recorded
        by the importer — the cluster counts the request exactly
        once), and bank the handoff for the router."""
        eng = self.eng
        book = self.book
        sid = r.rid
        t = self.clock.now()
        ids = book.export_chain(sid, len(r.prompt))
        n_exp = len(ids)
        data = eng.export_kv_pages(ids)
        q_idx: Tuple[int, ...] = ()
        if eng.kv_quant == "pressure":
            # the exported slices carry the device tier bits; the
            # chain POSITIONS in the int8 tier ride the handoff so
            # the importer can mirror them into its own bookkeeper
            # (pool page ids are meaningless across pools)
            q_idx = tuple(i for i, p in enumerate(ids)
                          if p in book._quant)
        self.handoff_ready.append(KVHandoff(
            req=r, first_tok=int(first_tok), n_pages=n_exp,
            kv_data=data, n_cached=n_cached, t_admit=t_admit,
            t_first=t, t_ready=t, replica_from=self.replica,
            page_size=eng.page_size, tp=eng.tp_size,
            kv_quant=eng.kv_quant, quant_pages=q_idx,
            layout=getattr(eng.serving, "kv_layout_", "head_major")))
        book.free(sid)
        eng._g_resident.set(float(len(book._refs)))
        if self.acache is not None and r.adapter is not None:
            # the adapter pin moves with the request: the exporter
            # unpins (its bank retains the adapter evictable for the
            # next sharer), the importer re-pins at adoption
            self.acache.release(r.adapter, sid)
            eng._note_adapters(self.acache, self.m, t)
        gname = eng._schema_of(r)
        if self.gcache is not None and gname is not None:
            # the grammar pin moves with the request too: the
            # importer re-acquires and re-derives the DFA state from
            # the first token (the exporter advanced no stream, so
            # grammar token metrics are the IMPORTER's to count)
            self.gcache.release(gname, sid)
        self.free_slots.append(slot)
        self.free_slots.sort()
        self.slot_log.append((round(t, 6), "handoff", sid, slot))
        obs_metrics.REGISTRY.counter(
            "serving_kv_handoffs_total",
            "KV chains moved between prefill and decode workers",
            direction="export").inc()
        if self.tr is not None:
            self.tr.instant("handoff_export", t=t, track="engine",
                            rid=sid, pages=n_exp)
        eng._req_close(self.tr, r, t, "handoff", 0)
        self.m.forget(sid)
        self.inv_ok &= book.census_ok()
        return True

    def submit_handoff(self, h: KVHandoff):
        """Router-facing: queue an exported KV chain for adoption.
        The import runs inside ``_turn`` once this lane's clock
        reaches ``h.t_arrive`` (the router stamps it with the
        per-page transfer cost on the shared timeline) and a decode
        slot is free."""
        self.import_queue.append(h)

    def _transform_handoff(self, h: KVHandoff, steps):
        """Run the priced reshard/repage/transcode steps on the
        IMPORTER's clock — the ``adapter_upload`` discipline: each
        step is one ``_timed`` span on the engine track (per-page
        priced on a fixed clock via its ``<kind>_unit`` entry, flat
        default otherwise), which the ledger funnel books as its own
        first-class kind. Mutates the handoff's stamps in place as
        each step lands, so every step's output is the next step's
        honestly-described input and the downstream import/tier-mirror
        code reads destination-true metadata."""
        eng, clock, tr = self.eng, self.clock, self.tr
        r = h.req
        sid = r.rid
        if "kv_reshard" in steps:
            h.kv_data = eng._timed(
                tr, clock, "kv_reshard",
                lambda: eng.reshard_kv_pages(h.kv_data),
                rid=sid, units=h.n_pages, tp_from=h.tp,
                tp_to=eng.tp_size)
            h.tp = eng.tp_size
            self._note_reshard("tp")
        if "kv_repage" in steps:
            n_dst = -(-len(r.prompt) // eng.page_size)
            ps_from = h.page_size
            h.kv_data = eng._timed(
                tr, clock, "kv_repage",
                lambda: eng.repage_kv_pages(h.kv_data, ps_from,
                                            len(r.prompt)),
                rid=sid, units=n_dst, page_from=ps_from,
                page_to=eng.page_size)
            h.n_pages = n_dst
            h.page_size = eng.page_size
            self._note_reshard("page")
        if "kv_transcode" in steps:
            q_from = h.kv_quant
            h.kv_data = eng._timed(
                tr, clock, "kv_transcode",
                lambda: eng.transcode_kv_pages(h.kv_data, q_from),
                rid=sid, units=h.n_pages, codec_from=q_from or "fp",
                codec_to=eng.kv_quant)
            h.kv_quant = eng.kv_quant
            if eng.kv_quant == "pressure":
                # the transcode parked the WHOLE chain in the int8
                # tier (tier bits all set); the chain positions ride
                # quant_pages so the existing import mirror prices
                # the adopted chain in this pool's byte census
                h.quant_pages = tuple(range(h.n_pages))
            self._note_reshard("codec")

    def _note_reshard(self, axis: str):
        """Account one transform step: the labeled counter is CREATED
        on the first transform ever run (armed-only — a twin fleet's
        registry stays byte-identical to pre-hetero) and the session
        tally feeds the router's census fold at removal/bank time."""
        obs_metrics.REGISTRY.counter(
            "serving_handoff_resharded_total",
            "KV handoffs transformed on import, by mismatch axis",
            axis=axis).inc()
        self.handoff_resharded[axis] = \
            self.handoff_resharded.get(axis, 0) + 1

    def _import_handoffs(self) -> bool:
        """Adopt every deliverable handoff: allocate a fresh chain,
        scatter the exported page content into it, re-record the
        request (its real arrival, the admission that happened on the
        source, the first token at its source timestamp — the client
        already has it) and enter a decode slot. A handoff blocked on
        pages retries next turn as rows finish; blocked with nothing
        else running is a sizing error and refuses loudly."""
        eng = self.eng
        book = self.book
        clock, m, tr = self.clock, self.m, self.tr
        got = False
        while self.import_queue and self.free_slots:
            # deliverable = transfer complete by now. Submission order
            # is NOT delivery order (t_arrive scales with each chain's
            # page count), so scan the whole queue — gating on the
            # head alone would park a delivered chain behind a slower
            # transfer forever
            ready = [h for h in self.import_queue
                     if clock.now() >= h.t_arrive - 1e-12]
            if not ready:
                break
            h = min(ready, key=lambda x: (x.t_arrive, x.req.rid))
            r = h.req
            sid = r.rid
            # the compatibility verdict (raises UnstampedHandoffError
            # on a hand-built handoff that skipped the geometry
            # stamps): () = twin, adopt as-is — the pre-hetero path
            # bit-for-bit, zero transform spans
            steps = eng.handoff_steps(h)
            if steps is None:
                raise RuntimeError(
                    f"handoff {sid!r} was exported under kv_quant="
                    f"{h.kv_quant!r}/page_size={h.page_size} but this "
                    f"decode worker runs kv_quant={eng.kv_quant!r}/"
                    f"page_size={eng.page_size} — an untransformable "
                    "pairing (quantized sources only adopt same-codec; "
                    "pressure chains never re-page), so placement must "
                    "refuse it like the geometry filters once did")
            if steps and h.layout != getattr(eng.serving, "kv_layout_",
                                             "head_major"):
                raise RuntimeError(
                    f"handoff {sid!r} carries canonical layout "
                    f"{h.layout!r} but this worker's factory speaks "
                    f"{getattr(eng.serving, 'kv_layout_', 'head_major')!r}"
                    " — a transform cannot reinterpret a foreign "
                    "layout (mixed sim/real fleets cannot exchange KV)")
            aslot, a_up = 0, False
            if r.adapter is not None:
                if self.acache is None:
                    raise RuntimeError(
                        f"handoff {sid!r} names adapter "
                        f"{r.adapter!r} but this decode worker was "
                        "built without adapters= — disaggregated "
                        "adapter serving needs the store on BOTH "
                        "stages")
                try:
                    # the importer pays the paced upload too when its
                    # bank never saw this adapter (run inside the
                    # timed wrapper; counting waits for the adoption
                    # to succeed)
                    aslot, a_up = self.acache.acquire(
                        r.adapter, sid,
                        timed=lambda f: eng._timed(
                            tr, clock, "adapter_upload", f, rid=sid,
                            adapter=r.adapter))
                except MemoryError:
                    break  # bank fully pinned: retry as rows finish
            if r.schema is not None and self.gcache is None:
                # _schema_of goes silently None on a grammar-less
                # engine (the single-engine _validate path refuses
                # earlier); an ADOPTED row must refuse here instead
                # of free-running past its declared output contract
                raise RuntimeError(
                    f"handoff {sid!r} names schema {r.schema!r} but "
                    "this decode worker was built without grammar= "
                    "— disaggregated constrained serving needs the "
                    "store on BOTH stages")
            gname = eng._schema_of(r)
            gslot, g_up, gaut = 0, False, None
            if gname is not None:
                try:
                    # the importer compiles when its bank never saw
                    # this schema — the priced clock action fires
                    # here, on adoption, like adapter_upload above
                    gslot, g_up = self.gcache.acquire(
                        gname, sid,
                        timed=lambda f: eng._timed(
                            tr, clock, "grammar_compile", f, rid=sid,
                            schema=gname))
                except MemoryError:
                    if r.adapter is not None \
                            and self.acache is not None:
                        self.acache.note_rollback(r.adapter, sid,
                                                  a_up)
                    break  # bank fully pinned: retry as rows finish
                gaut = self.gcache.automaton(gname)
            try:
                book.allocate(sid, eng._footprint(r))
            except MemoryError:
                if r.adapter is not None and self.acache is not None:
                    self.acache.note_rollback(r.adapter, sid, a_up)
                if gname is not None:
                    self.gcache.note_rollback(gname, sid, g_up)
                if not self.active and not (self.lane or ()) \
                        and not self.queued():
                    raise RuntimeError(
                        f"pool too small to import handoff {sid!r} "
                        f"(free pages {len(book._free)}, needs "
                        f"{eng._footprint(r)} tokens)")
                break
            if r.adapter is not None:
                a_up = self.acache.took_upload(sid, a_up)
                (eng._ctr_adapter_uploads if a_up
                 else eng._ctr_adapter_hits).inc()
            if gname is not None:
                g_up = self.gcache.took_compile(sid, g_up)
                (eng._ctr_grammar_compiles if g_up
                 else eng._ctr_grammar_hits).inc()
                m.on_grammar(sid, gname, hit=not g_up)
            self.import_queue.remove(h)
            book.lengths[sid] = len(r.prompt)
            if steps:
                # priced on THIS clock only now — after the chain
                # allocated, so a page-blocked import that retried
                # across turns never charged for transforms it had to
                # redo, and a twin import runs zero extra spans
                self._transform_handoff(h, steps)
            eng.import_kv_pages(book.tables[sid][:h.n_pages],
                                h.kv_data)
            if h.kv_quant == "pressure" and h.quant_pages:
                # the scattered data restored the device tier bits;
                # mirror them in this pool's bookkeeper so the byte
                # census prices the adopted chain by its real tier
                tbl = book.tables[sid]
                book.mark_quantized([tbl[i] for i in h.quant_pages])
            if eng.prefix_cache:
                # the imported prompt pages hold real K/V: publish
                # them, so sharers landing on this decode worker hit
                book.register_prefix(sid, list(r.prompt))
            slot = self.free_slots.pop(0)
            t = clock.now()
            m.on_arrival(sid, r.arrival, tenant=r.tenant,
                         priority=r.priority,
                         deadline_ms=r.deadline_ms)
            eng._req_open(tr, r)
            m.on_admit(sid, h.t_admit, "paged")
            obs_metrics.REGISTRY.counter(
                "serving_kv_handoffs_total",
                "KV chains moved between prefill and decode workers",
                direction="import").inc()
            if tr is not None:
                tr.instant("handoff_import", t=t, track="engine",
                           rid=sid, pages=h.n_pages,
                           source=h.replica_from)
            if r.adapter is not None:
                m.on_adapter(sid, r.adapter, hit=not a_up)
                eng._note_adapters(self.acache, m, t)
            gstate = 0
            row = _PagedRow(r, slot, h.first_tok, t0=t, aslot=aslot,
                            gslot=gslot, gname=gname, gaut=gaut)
            if gaut is not None:
                # the exporter advanced no stream: the first token's
                # DFA step — and its grammar token metrics — land on
                # the importer, mirroring m.on_tokens below
                gstate = gaut.start
                mf = gaut.masked_frac(gstate)
                row.gmasked += mf
                m.on_grammar_tokens(1, mf)
                gstate = gaut.step(gstate, int(h.first_tok))
                row.gstate = gstate
                if gaut.accepts_at(gstate):
                    row.done = True
                    m.on_grammar_accept(sid, h.t_first)
                    if tr is not None:
                        tr.instant("grammar_accept", t=h.t_first,
                                   track=eng._tenant_track(r),
                                   rid=sid, schema=gname)
            self.active[sid] = row
            self.slot_log.append((round(t, 6), "acquire", sid, slot))
            self.prefix_cached[sid] = 0
            m.on_tokens(sid, h.t_first, 1)
            eng._ctr_tokens.inc()
            if tr is not None:
                tr.instant("first_token", t=h.t_first,
                           track=eng._tenant_track(r), rid=sid)
            self.handoff_stats["imported"] += 1
            eng._g_resident.set(float(len(book._refs)))
            got = True
        return got

    # --- the drive loop ----------------------------------------------------
    def _shed(self, pairs) -> bool:
        eng = self.eng
        for r, reason in pairs:
            t = self.clock.now()
            self.m.on_shed(r.rid, t, reason)
            self.shed_log[r.rid] = reason
            eng._ctr_shed.inc()
            if self.acache is not None:
                self.acache.forget_pending(r.rid)
            if self.gcache is not None:
                self.gcache.forget_pending(r.rid)
            if self.hst is not None \
                    and r.rid in self.hst["preempted"]:
                # preempted-then-shed: the pinned chain never pages
                # back in — release its arena bytes
                self.hst["preempted"].discard(r.rid)
                self.book.drop_spilled_owner(r.rid)
            if self.tr is not None:
                self.tr.instant("shed", t=t, track="scheduler",
                                rid=r.rid, reason=reason,
                                tenant=r.tenant)
            eng._req_close(self.tr, r, t, "shed", 0, reason=reason)
        return bool(pairs)

    def _ready(self) -> bool:
        """run()'s admission-window test with ``more_expected``
        standing in for the trace's pending deque. The comparison uses
        the IDENTICAL float expression ``oldest + max_delay`` as
        ``_idle_target`` — advance_to(target) must always read as
        ready on arrival, or one ulp of a large clock livelocks the
        advance loop (see ``_admission_ready``)."""
        if self.queued() >= self.eng.admission.max_batch:
            return True
        oldest = self.sched.oldest_arrival() if self.sched is not None \
            else self.waiting[0].arrival
        if self.clock.now() >= oldest \
                + self.eng.admission.max_delay - 1e-12:
            return True
        return not self.more_expected and not self.active

    def _idle_target(self) -> Optional[float]:
        """When nothing progressed and nothing runs: the time the
        oldest waiting request's admission window closes, or the next
        queued handoff's delivery time — whichever is sooner (None
        with neither: only a new arrival can wake this lane)."""
        targets = []
        if self.queued():
            oldest = self.sched.oldest_arrival() \
                if self.sched is not None else self.waiting[0].arrival
            targets.append(oldest + self.eng.admission.max_delay)
        now = self.clock.now()
        future = [h.t_arrive for h in self.import_queue
                  if h.t_arrive > now + 1e-12]
        if future:
            # already-delivered-but-blocked handoffs define no idle
            # target: they import the moment a slot/pages free, and
            # an in-the-past target would spin the advance loop
            targets.append(min(future))
        return min(targets) if targets else None

    def _turn(self) -> bool:
        """One scheduler turn: admission attempt + decode chunk —
        run()'s / _run_scheduled's loop body minus arrival ingestion
        (the router owns arrivals)."""
        eng = self.eng
        clock, tr, m = self.clock, self.tr, self.m
        now = clock.now()
        m.on_queue_depth(now, self.queued())
        # decode-slot utilization (busy slots / capacity), sampled
        # once per turn like queue depth: the live gauge any scrape
        # reads, and — through the collector — the SLO-watchable
        # `replica_busy_frac` signal the autoscaler's drain decision
        # stands on (`ThresholdRule(signal="replica_busy_frac")`)
        busy = (eng.slots - self.free_slot_count()) / eng.slots
        m.on_busy_frac(now, busy)
        if self._g_busy is not None:
            self._g_busy.set(busy)
        if tr is not None:
            tr.counter("queue_depth", self.queued(), t=now)
        progressed = False
        if self.import_queue:
            # adopt deliverable handoffs first, so the imported row
            # joins this turn's decode batch
            progressed |= self._import_handoffs()
        if self.sched is not None:
            progressed |= self._shed(self.sched.shed_expired(now))
            if self.sched.waiting() and self._ready():
                progressed |= self._qos_wave(now)
        elif self.waiting and self._ready():
            progressed |= self._fifo_wave()
        if self.active:
            t0 = clock.now()
            try:
                if self.decode_fault_hook is not None:
                    self.decode_fault_hook(self)
                eng._paged_chunk(self.book, clock, m, self.active,
                                 self.free_slots, self.slot_log,
                                 self.outputs, tr=tr,
                                 acache=self.acache, spst=self.spst,
                                 ahst=self.ahst, gcache=self.gcache)
            except DecodeError as e:
                # one slot's computation failed: tear down exactly
                # that row (the decode turn is forfeit — survivors
                # resume next turn with their state intact) and bank
                # it for the driver to fail over
                if e.rid not in self.active:
                    raise
                self.aborted.append(
                    self.abort_row(e.rid, reason="decode_error"))
            else:
                if self.est is not None:
                    self.est.observe("decode", clock.now() - t0)
            if self.est is not None:
                # the deadline-timeout scan runs whether the decode
                # turn completed or aborted — an expired row must not
                # survive an extra chunk just because another slot's
                # fault forfeited this turn
                t = clock.now()
                for sid in list(self.active):
                    dl = self.active[sid].req.deadline_time()
                    if dl is not None and t > dl + 1e-9:
                        eng._finish_paged(sid, self.book, clock, m,
                                          self.active,
                                          self.free_slots,
                                          self.slot_log,
                                          self.outputs,
                                          timeout=True, tr=tr,
                                          acache=self.acache,
                                          gcache=self.gcache)
            progressed = True
        if self.lane:
            sink = self._handoff_sink if self.role == "prefill" \
                else None
            _, ptoks = eng._lane_step(
                self.lane, self.book, clock, m, self.active,
                self.free_slots, self.slot_log, self.outputs,
                self.prefix_cached, self.seen_groups, tr=tr,
                sink=sink, acache=self.acache, spst=self.spst,
                gcache=self.gcache)
            self.prefill_tokens += ptoks
            if self.est is not None:
                eng._lane_timeouts(self.lane, self.book, clock, m,
                                   self.free_slots, self.slot_log,
                                   self.outputs, tr=tr,
                                   acache=self.acache,
                                   gcache=self.gcache)
            progressed = True
        eng._quant_turn(self.book, m, clock, tr, self.qst)
        self.inv_ok &= self.book.census_ok()
        if self.acache is not None:
            self.a_inv_ok &= self.acache.census_ok()
        if self.gcache is not None:
            self.g_inv_ok &= self.gcache.census_ok()
        if eng._ledger is not None:
            eng._ledger.sample_occupancy(
                clock.label, book=self.book, acache=self.acache,
                gcache=self.gcache,
                arena=getattr(self.book, "_arena", None))
        return progressed

    def _route_ctx(self, wave):
        groups = [r.prefix_group for r in wave
                  if r.prefix_group is not None]
        shared = (len(groups) != len(set(groups))
                  or any(g in self.seen_groups for g in groups))
        return groups, dict(self._ctx_base, shared_prefix=shared,
                            active_paged=len(self.active)
                            + len(self.lane or ()))

    def _fifo_wave(self) -> bool:
        eng, clock, tr, m = self.eng, self.clock, self.tr, self.m
        wave = self.waiting[:eng.admission.max_batch]
        groups, ctx = self._route_ctx(wave)
        backend, reason = eng.policy.route(wave, ctx)
        decision = {"t": round(clock.now(), 6), "wave": len(wave),
                    "prompt_lens": [len(r.prompt) for r in wave],
                    "backend": backend, "rule": reason}
        if backend == "dense":
            self.decisions.append(decision)
            eng._wave_instant(tr, decision)
            del self.waiting[:len(wave)]
            self.seen_groups.update(g for g in groups)
            eng._run_dense_wave(wave, clock, m, self.outputs, tr=tr)
            return True
        wave = eng._order_wave(wave)
        n_adm, _, ptoks = eng._admit_paged(
            wave, self.book, clock, m, self.active, self.free_slots,
            self.slot_log, self.prefix_cached, self.seen_groups,
            self.outputs, tr=tr, lane=self.lane,
            sink=(self._handoff_sink if self.role == "prefill"
                  else None), acache=self.acache, spst=self.spst,
            hst=self.hst, gcache=self.gcache)
        self.prefill_tokens += ptoks
        for r in wave[:n_adm]:
            self.waiting.remove(r)  # possibly reordered: by identity
        if n_adm:
            decision["admitted"] = n_adm
            decision["admit_rids"] = [r.rid for r in wave[:n_adm]]
            self.decisions.append(decision)
            eng._wave_instant(tr, decision)
        elif not self.active and not self.lane \
                and not self.import_queue:
            raise RuntimeError(
                f"pool/slot config too small for {wave[0].rid} (free "
                f"pages {len(self.book._free)}, free slots "
                f"{len(self.free_slots)})")
        return n_adm > 0

    def _qos_wave(self, now: float) -> bool:
        eng, clock, tr, m = self.eng, self.clock, self.tr, self.m
        dec = self.sched.select(
            now, max_batch=eng.admission.max_batch, est=self.est,
            decode_chunk=eng.decode_chunk,
            match_prefix=(self.book.match_prefix if eng.prefix_cache
                          else None),
            backlog_cost=(eng._lane_backlog_cost(self.lane, self.est)
                          if self.lane else 0.0))
        progressed = self._shed(dec.shed)
        wave = dec.wave
        if not wave:
            return progressed
        groups, ctx = self._route_ctx(wave)
        backend, reason = eng.policy.route(wave, ctx)
        decision = {"t": round(clock.now(), 6), "wave": len(wave),
                    "prompt_lens": [len(r.prompt) for r in wave],
                    "backend": backend, "rule": reason,
                    "rids": [r.rid for r in wave]}
        if backend == "dense":
            self.decisions.append(decision)
            eng._wave_instant(tr, decision)
            self.seen_groups.update(g for g in groups)
            eng._commit_wave(wave, dec, self.sched, m, tr=tr,
                             t=clock.now())
            eng._run_dense_wave(wave, clock, m, self.outputs,
                                timeouts=True, tr=tr)
            return True
        t0 = clock.now()
        n_adm, n_chunks, ptoks = eng._admit_paged(
            wave, self.book, clock, m, self.active, self.free_slots,
            self.slot_log, self.prefix_cached, self.seen_groups,
            self.outputs, tr=tr, lane=self.lane,
            sink=(self._handoff_sink if self.role == "prefill"
                  else None), acache=self.acache, spst=self.spst,
            hst=self.hst, gcache=self.gcache)
        self.prefill_tokens += ptoks
        if n_adm:
            dt = clock.now() - t0
            self.est.observe("prefill", dt / n_adm)
            if n_chunks and "prefill_unit" in self.est.costs:
                self.est.observe("prefill_unit", dt / n_chunks)
            eng._commit_wave(wave[:n_adm], dec, self.sched, m, tr=tr,
                             t=clock.now())
            decision["admitted"] = n_adm
            self.decisions.append(decision)
            eng._wave_instant(tr, decision)
            return True
        if self.hst is not None and self.active \
                and eng._preempt_turn(wave[0], self.book, clock, m,
                                      self.active, self.free_slots,
                                      self.slot_log, self.sched,
                                      self.hst, self._shed, tr=tr,
                                      acache=self.acache,
                                      gcache=self.gcache):
            return True
        if not self.active and not self.lane \
                and not self.import_queue:
            raise RuntimeError(
                f"pool/slot config too small for {wave[0].rid} (free "
                f"pages {len(self.book._free)}, free slots "
                f"{len(self.free_slots)})")
        return progressed

    def advance_until(self, t: float):
        """Process this lane up to virtual time ``t``. Compute may
        overshoot ``t`` (a decode chunk crossing the horizon models a
        busy replica — same as the single-engine loop); an idle lane's
        clock jumps straight to ``t`` so later submissions see honest
        queueing delays.

        A CRASHED session advances its clock but processes nothing (a
        dead process has no turns). A STALLED session does the same
        until ``stall_until`` passes, then resumes mid-call — queued
        and in-flight work eats the pause, exactly the transient-slow
        replica the failure detector must NOT declare dead."""
        if self.crashed:
            self.clock.advance_to(t)
            return
        if self.stall_until is not None:
            if t < self.stall_until - 1e-12:
                self.clock.advance_to(t)
                return
            self.clock.advance_to(self.stall_until)
            self.stall_until = None
        while True:
            if self.queued() == 0 and not self.active \
                    and not self.lane and not self.import_queue:
                self.clock.advance_to(t)
                return
            if self.clock.now() >= t - 1e-12:
                return
            progressed = self._turn()
            if not progressed and not self.active and not self.lane:
                target = self._idle_target()
                if target is not None and target <= t:
                    self.clock.advance_to(target)
                else:
                    self.clock.advance_to(t)
                    return

    def finish(self) -> ServeResult:
        """No more arrivals will ever reach this session: run the
        backlog dry and build the ServeResult (idempotent)."""
        if self._finished is not None:
            return self._finished
        self.more_expected = False
        # a stall outliving the driven timeline is still real time:
        # the final backlog drain must eat the remaining pause, not
        # skip it (advance_until honors stalls; this loop drives
        # _turn directly)
        if self.stall_until is not None and not self.crashed:
            self.clock.advance_to(self.stall_until)
            self.stall_until = None
        # a crashed session has nothing left to run (its rows were
        # torn down at crash; its queue is rescued by the router) —
        # its result banks only the work that finished before death
        while not self.crashed and (self.queued() or self.active
                                    or self.lane
                                    or self.import_queue):
            progressed = self._turn()
            if not progressed and not self.active and not self.lane:
                target = self._idle_target()
                if target is None:
                    break  # everything left this turn was shed
                self.clock.advance_to(target)
        ServingEngine._stitch_resumes(self.outputs, self.hst)
        self._finished = ServeResult(
            policy=self.eng.policy.name, outputs=self.outputs,
            metrics=self.m, decisions=self.decisions,
            slot_log=self.slot_log, prefix_cached=self.prefix_cached,
            pages_total=self.pages_total,
            pages_free_end=(len(self.book._free)
                            + len(self.book._evictable)),
            scheduler=("fifo" if self.sched is None
                       else self.sched.name),
            shed=self.shed_log, trace=self.tr,
            prefill_tokens=self.prefill_tokens,
            cache_stats=dict(self.book.cache_stats(),
                             invariant_ok=self.inv_ok),
            replica=self.replica,
            incidents=ServingEngine._bank_incidents(self.slo),
            adapter_stats=(
                None if self.acache is None else
                dict(self.acache.cache_stats(),
                     invariant_ok=self.a_inv_ok)),
            spec_stats=(None if self.spst is None
                        else self.spst.stats()),
            kv_quant_stats=self.eng._quant_result(self.book,
                                                  self.qst),
            hostmem_stats=self.eng._hostmem_result(self.book,
                                                   self.hst),
            pages_spilled=(
                None if self.hst is None else
                self.book.cache_stats().get("spilled_pages", 0)),
            grammar_stats=(
                None if self.gcache is None else
                dict(self.gcache.cache_stats(),
                     invariant_ok=self.g_inv_ok)),
            cost_stats=self.eng._cost_result(self.clock, self.tr,
                                             self.m))
        return self._finished
